"""Versioned trace format + synthetic workload generators.

A trace is a plain JSON-serializable dict — a first-class, replayable
artifact. Version 1 shape:

    {
      "version": 1,
      "name": "steady-state",
      "duration": 300.0,            # virtual seconds simulated
      "tick": 1.0,                  # controller pass interval (virtual s)
      "nodepools": [
        {"name": "workers", "consolidate_after": 15.0,
         "requirements": [...], "limits": {...}}        # optional extras
      ],
      "faults": {                   # probabilistic per-call fault rates
        "launch_failure_rate": 0.0,        # CreateError (retryable)
        "insufficient_capacity_rate": 0.0, # ICE (claim deleted, re-solved)
        "ack_then_raise_rate": 0.0,        # create LANDS, response lost —
                                           #   retry must converge by key
        "api_latency": 0.0,                # virtual s added per cloud call
        "api_jitter": 0.0,                 # + uniform[0, jitter)
        "solver_rejection_rate": 0.0,      # QueueFullError per solve
        "outages": [                       # scheduled FULL cloud-API
          {"at": 150.0, "duration": 50.0}  #   outages: every create/delete
        ]                                  #   raises (untyped, retryable)
      },
      "events": [                   # sorted by "at" (virtual s from start)
        {"at": 5.0, "kind": "submit", "group": "web", "count": 6,
         "pod": {"cpu": "1", "memory": "1Gi",
                 "capacity_type": "spot",     # optional nodeSelector pins
                 "zone": "...", "arch": "...",
                 "labels": {...},
                 "spread": "zone"},           # topology-spread on zone
         "until": 200.0,            # group completes (pods deleted); omit
                                    #   to run to end of trace
         "replace": true},          # ReplicaSet stand-in: deleted pods are
                                    #   resubmitted until "until"
        {"at": 90.0, "kind": "interrupt", "count": 1,
         "mode": "graceful",        # delete NodeClaim (interruption notice)
         "capacity_type": "spot"},  # victim filter
        {"at": 150.0, "kind": "interrupt", "count": 1, "mode": "reclaim"},
        {"at": 180.0, "kind": "operator-crash",  # arm a one-shot kill at a
         "barrier": "post-intent-pre-effect",    #   journal barrier: also
                                                 #   pre-intent /
                                                 #   post-effect-pre-done
         "action": "nodeclaim.launch"}           # optional: fire only on
                                                 #   this intent type
      ]
    }

Generators are pure functions of a seeded ``random.Random`` — the same seed
always yields the same trace, which (with the harness's seeded uid source)
makes whole runs byte-reproducible.
"""

from __future__ import annotations

import json
import math
from random import Random

TRACE_VERSION = 1


def validate(trace: dict) -> dict:
    """Cheap structural validation; returns the trace for chaining."""
    if trace.get("version") != TRACE_VERSION:
        raise ValueError(
            f"unsupported trace version {trace.get('version')!r} "
            f"(this build reads version {TRACE_VERSION})"
        )
    for key in ("name", "duration", "events"):
        if key not in trace:
            raise ValueError(f"trace missing required key {key!r}")
    last = -math.inf
    for ev in trace["events"]:
        if "at" not in ev or "kind" not in ev:
            raise ValueError(f"trace event missing at/kind: {ev!r}")
        if ev["at"] < last:
            raise ValueError("trace events must be sorted by 'at'")
        last = ev["at"]
    if "fleet" in trace:
        fleet = trace["fleet"]
        if int(fleet.get("replicas", 0)) < 1:
            raise ValueError("fleet trace needs fleet.replicas >= 1")
        tenants = trace.get("tenants")
        if not tenants:
            raise ValueError("fleet trace needs a non-empty 'tenants' list")
        names = set()
        for tenant in tenants:
            if "name" not in tenant or "trace" not in tenant:
                raise ValueError(f"fleet tenant missing name/trace: {tenant!r}")
            if tenant["name"] in names:
                raise ValueError(f"duplicate fleet tenant {tenant['name']!r}")
            names.add(tenant["name"])
            validate(tenant["trace"])
        last = -math.inf
        for kill in fleet.get("kills", []):
            if "at" not in kill or "replica" not in kill:
                raise ValueError(f"fleet kill missing at/replica: {kill!r}")
            if not 0 <= int(kill["replica"]) < int(fleet["replicas"]):
                raise ValueError(f"fleet kill names unknown replica: {kill!r}")
            if kill["at"] < last:
                raise ValueError("fleet kills must be sorted by 'at'")
            last = kill["at"]
    return trace


def loads(text: str) -> dict:
    return validate(json.loads(text))


def dumps(trace: dict) -> str:
    return json.dumps(trace, sort_keys=True, indent=2)


def _base(name: str, duration: float, tick: float = 1.0) -> dict:
    return {
        "version": TRACE_VERSION,
        "name": name,
        "duration": duration,
        "tick": tick,
        "nodepools": [{"name": "workers", "consolidate_after": 15.0}],
        "faults": {},
        "events": [],
    }


# -- generators ---------------------------------------------------------------


def steady_state(rng: Random) -> dict:
    """A constant web-service footprint: one burst of service pods that run
    for the whole trace, plus a small mid-run scale-up. No faults — this is
    the baseline whose digest should never move."""
    trace = _base("steady-state", duration=240.0)
    trace["events"] = [
        {
            "at": 4.0,
            "kind": "submit",
            "group": "web",
            "count": 5 + rng.randrange(3),
            "pod": {"cpu": str(1 + rng.randrange(2)), "memory": "1Gi"},
            "replace": True,
        },
        {
            "at": 120.0,
            "kind": "submit",
            "group": "web-scaleup",
            "count": 2 + rng.randrange(2),
            "pod": {"cpu": "1", "memory": "1Gi"},
            "replace": True,
        },
    ]
    return trace


def spot_interruption(rng: Random) -> dict:
    """Spot-pinned service pods under repeated capacity interruptions: one
    graceful (interruption-notice → NodeClaim delete → drain → replacement)
    and one hard reclaim (instance vanishes out-of-band → GC reaps the
    claim → replacement). Exercises the NodeClaim retry/replacement path."""
    trace = _base("spot-interruption", duration=420.0)
    trace["events"] = [
        {
            "at": 4.0,
            "kind": "submit",
            "group": "spotty",
            "count": 4 + rng.randrange(3),
            "pod": {"cpu": "2", "memory": "2Gi", "capacity_type": "spot"},
            "replace": True,
        },
        {"at": 60.0, "kind": "interrupt", "count": 1, "mode": "graceful",
         "capacity_type": "spot"},
        {"at": 140.0, "kind": "interrupt", "count": 1, "mode": "reclaim",
         "capacity_type": "spot"},
    ]
    return trace


def diurnal(rng: Random) -> dict:
    """Diurnal web traffic, a day compressed into the trace: pod arrivals
    follow a sinusoid — waves submitted on the upswing, completing on the
    downswing — so the autoscaler rides scale-up AND consolidation."""
    duration, waves = 600.0, 6
    trace = _base("diurnal", duration=duration, tick=2.0)
    events = [
        {
            "at": 4.0,
            "kind": "submit",
            "group": "base",
            "count": 2,
            "pod": {"cpu": "1", "memory": "1Gi"},
            "replace": True,
        }
    ]
    for i in range(waves):
        at = 20.0 + i * (duration - 80.0) / waves
        # sinusoidal demand: peak mid-trace
        level = math.sin(math.pi * (i + 1) / (waves + 1))
        count = max(1, round(level * (4 + rng.randrange(3))))
        events.append(
            {
                "at": round(at, 3),
                "kind": "submit",
                "group": f"wave-{i}",
                "count": count,
                "pod": {"cpu": str(rng.choice([1, 1, 2])), "memory": "2Gi"},
                "until": round(min(at + 120.0 + rng.randrange(60), duration - 30.0), 3),
                "replace": True,
            }
        )
    trace["events"] = sorted(events, key=lambda e: e["at"])
    return trace


def batch_waves(rng: Random) -> dict:
    """Batch-job waves: bursts of short-lived jobs arriving every ~90s,
    each wave finishing before the next two land — steady churn through
    provisioning, completion, and empty-node consolidation."""
    duration = 480.0
    trace = _base("batch-waves", duration=duration, tick=2.0)
    events = []
    at = 6.0
    i = 0
    while at < duration - 120.0:
        runtime = 60.0 + rng.randrange(40)
        events.append(
            {
                "at": round(at, 3),
                "kind": "submit",
                "group": f"job-{i}",
                "count": 3 + rng.randrange(4),
                "pod": {"cpu": "4", "memory": "8Gi"},
                "until": round(at + runtime, 3),
                "replace": False,  # batch pods that die stay dead
            }
        )
        at += 80.0 + rng.randrange(30)
        i += 1
    trace["events"] = events
    return trace


def tpu_training(rng: Random) -> dict:
    """TPU-slice-shaped training jobs: gangs of large workers spread across
    zones (one slice per failure domain, the topology-spread discipline
    multislice training uses), pinned to arm64 hosts, long-running."""
    trace = _base("tpu-training", duration=360.0, tick=2.0)
    trace["events"] = [
        {
            "at": 4.0,
            "kind": "submit",
            "group": "trainer",
            "count": 4,
            "pod": {
                "cpu": "16",
                "memory": "64Gi",
                "arch": "arm64",
                "spread": "zone",
                "labels": {"app": "trainer"},
            },
            "replace": True,
        },
        {
            "at": 90.0,
            "kind": "submit",
            "group": "eval",
            "count": 2 + rng.randrange(2),
            "pod": {"cpu": "8", "memory": "16Gi", "arch": "arm64"},
            "until": 250.0,
            "replace": True,
        },
    ]
    return trace


def mesh_sweep(rng: Random) -> dict:
    """A shape-diverse fleet wide enough to engage the DEVICE feasibility
    sweep under the sim's pinned routing: each wave submits dozens of
    distinct (zone, arch, capacity-type, size) combinations in ONE batch,
    so the joint-mask priming sweep crosses the device-RTT threshold
    instead of taking the host twin (every other scenario's one-or-two-
    shape batches stay host-side). The second wave lands NEW shapes in the
    SAME padded bucket — post-seal device dispatches that must not
    recompile. This is the mesh-smoke scenario: sharded dispatches pad to
    mesh-size-invariant global shapes, so runs at --shard-devices 1 and 8
    must produce byte-identical event AND kernel digests."""
    trace = _base("mesh-sweep", duration=240.0)
    zones = ["kwok-zone-1", "kwok-zone-2", "kwok-zone-3", "kwok-zone-4"]
    cpus = ["500m", "1", "2", "4"]
    mems = ["1Gi", "2Gi", "4Gi"]
    # the full selector cross product: 5 zone options x 3 arch x 2 capacity
    # = 30 distinct requirement ROW-SETS in one batch — wide enough that the
    # joint-mask priming sweep (P2=32, R2~16 against the 144x1152 kwok
    # catalog) clears the pinned-RTT device threshold
    combos = [
        (z, a, c)
        for z in [None, *zones]
        for a in (None, "amd64", "arm64")
        for c in (None, "spot")
    ]

    def wave(salt: int, at: float, until=None) -> list[dict]:
        events = []
        for i, (zone, arch, ct) in enumerate(combos):
            pod = {"cpu": cpus[(i + salt) % 4], "memory": mems[(i + salt) % 3]}
            if zone:
                pod["zone"] = zone
            if arch:
                pod["arch"] = arch
            if ct:
                pod["capacity_type"] = ct
            ev = {
                "at": at,
                "kind": "submit",
                "group": f"sweep-{salt}-{i}",
                # 3-4 pods per combo: the wave lands ~100 pods in ONE
                # provisioner batch, clearing ffd.DEVICE_MIN_PODS so the
                # solve takes the device fast path (every other scenario's
                # batches fall back to the host scan)
                "count": 3 + rng.randrange(2),
                "pod": pod,
                "replace": True,
            }
            if until is not None:
                ev["until"] = until
            events.append(ev)
        return events

    trace["events"] = wave(0, 4.0) + wave(1, 120.0, until=200.0)
    return trace


def consolidation_churn(rng: Random) -> dict:
    """The consolidation-heavy shape the frontier search exists for: waves
    of large short-lived pods fan the cluster out to many nodes, each
    wave leaving behind a residue of small long-running pods — so after a
    wave drains, the fleet is many barely-utilized (non-empty) nodes that
    only MULTI-node consolidation can fold together. Two full
    fan-out/drain/consolidate cycles, no faults: the event digest is a pure
    function of the frontier search's decisions."""
    duration = 540.0
    trace = _base("consolidation-churn", duration=duration, tick=2.0)
    # pin the pool to 4-cpu boxes: a 3-cpu fanout pod then owns a node, so
    # a drained wave strands its residue across MANY small nodes — the
    # multi-node shape. (On the default catalog the packer would fold the
    # whole wave onto a couple of 16x machines and consolidation would
    # never see a multi-node fleet.)
    trace["nodepools"][0]["requirements"] = [
        {
            "key": "karpenter.kwok.sh/instance-size",
            "operator": "In",
            "values": ["4x"],
        }
    ]
    # let consolidation act on the whole drained fleet at once — the
    # default 10% budget admits one node on a fleet this size, which would
    # push everything through the single-node path
    trace["nodepools"][0]["budgets"] = [{"nodes": "100%"}]
    events = []
    for cycle in range(2):
        start = 6.0 + cycle * 240.0
        spreaders = 8 + rng.randrange(4)
        # the fan-out: one fat pod per node, gone after ~100s
        events.append(
            {
                "at": round(start, 3),
                "kind": "submit",
                "group": f"fanout-{cycle}",
                "count": spreaders,
                "pod": {"cpu": "3", "memory": "4Gi"},
                "until": round(start + 90.0 + rng.randrange(20), 3),
                "replace": False,
            }
        )
        # the residue: small long-running pods left stranded one-per-node,
        # keeping the drained nodes non-empty (underutilized, not empty)
        events.append(
            {
                "at": round(start + 2.0, 3),
                "kind": "submit",
                "group": f"residue-{cycle}",
                "count": spreaders,
                "pod": {"cpu": "200m", "memory": "256Mi"},
                "replace": True,
            }
        )
    trace["events"] = sorted(events, key=lambda e: e["at"])
    return trace


def solverd_restart(rng: Random) -> dict:
    """Service load with the solver daemon restarting mid-trace — the
    rolling-upgrade path: steady demand establishes a warm solver, the
    restart drops every engine and executable, and a scale-up lands right
    after it so the very next solve pays the restart's cold path. With the
    AOT compile service configured (--compile-cache-dir), that cold path
    warm-starts from the persistent executable cache; either way the run
    must complete deterministically with every pod bound (no SLO breach)."""
    trace = _base("solverd-restart", duration=300.0)
    trace["events"] = [
        {
            "at": 4.0,
            "kind": "submit",
            "group": "svc",
            "count": 4 + rng.randrange(3),
            "pod": {"cpu": "2", "memory": "2Gi"},
            "replace": True,
        },
        {
            "at": 60.0,
            "kind": "submit",
            "group": "batch",
            "count": 2 + rng.randrange(2),
            "pod": {"cpu": "1", "memory": "1Gi"},
            "until": 220.0,
            "replace": True,
        },
        # the daemon restarts mid-stream (rolling upgrade) ...
        {"at": 150.0, "kind": "solverd-restart"},
        # ... and demand arrives immediately after, forcing the first
        # post-restart solve through the rebuilt (warm-started) engine
        {
            "at": 160.0,
            "kind": "submit",
            "group": "post-restart",
            "count": 3 + rng.randrange(2),
            "pod": {"cpu": "1", "memory": "2Gi"},
            "replace": True,
        },
    ]
    return trace


def fleet_replica_kill(rng: Random) -> dict:
    """The solverd-fleet availability gauntlet: three tenant clusters with
    distinct workload shapes share a 2-replica solver pool, and one replica
    is killed (SIGKILL — no drain, no goodbye) mid-trace. The survivors'
    client-side breakers must open, affinity routing must converge on the
    surviving replica, and every tenant's demand — including a post-kill
    scale-up landing right on the failover path — must still bind with no
    pod left unschedulable and zero double-executed solves."""
    duration = 240.0
    trace = {
        "version": TRACE_VERSION,
        "name": "fleet-replica-kill",
        "duration": duration,
        "tick": 2.0,
        "fleet": {
            "replicas": 2,
            # a modest per-tenant quota: big enough that well-behaved
            # tenants never trip it, live so the quota metrics are
            # exercised end to end
            "tenant_quota": 32,
            "kills": [{"at": 120.0, "replica": 0}],
        },
        "tenants": [],
        "events": [],
    }

    def tenant(name: str, weight: float, events: list) -> dict:
        return {
            "name": name,
            "weight": weight,
            "trace": {
                "version": TRACE_VERSION,
                "name": f"{name}-stream",
                "duration": duration,
                "tick": 2.0,
                "nodepools": [{"name": "workers", "consolidate_after": 15.0}],
                "faults": {},
                "events": sorted(events, key=lambda e: e["at"]),
            },
        }

    # tenant-web: steady service footprint, weighted heaviest
    trace["tenants"].append(
        tenant(
            "tenant-web",
            2.0,
            [
                {
                    "at": 4.0,
                    "kind": "submit",
                    "group": "web",
                    "count": 4 + rng.randrange(3),
                    "pod": {"cpu": "2", "memory": "2Gi"},
                    "replace": True,
                },
                # a scale-up right after the kill, sized so it cannot bind
                # onto existing headroom: the very next solves MUST ride the
                # failover path onto the surviving replica
                {
                    "at": 130.0,
                    "kind": "submit",
                    "group": "web-scaleup",
                    "count": 2 + rng.randrange(2),
                    "pod": {"cpu": "16", "memory": "32Gi"},
                    "replace": True,
                },
                {
                    "at": 170.0,
                    "kind": "submit",
                    "group": "web-burst",
                    "count": 2,
                    "pod": {"cpu": "16", "memory": "32Gi"},
                    "until": 220.0,
                    "replace": True,
                },
            ],
        )
    )
    # tenant-batch: short-lived job waves, churning before and after the kill
    batch_events = []
    at = 6.0
    i = 0
    while at < duration - 60.0:
        batch_events.append(
            {
                "at": round(at, 3),
                "kind": "submit",
                "group": f"job-{i}",
                "count": 2 + rng.randrange(3),
                "pod": {"cpu": "2", "memory": "4Gi"},
                "until": round(at + 50.0 + rng.randrange(20), 3),
                "replace": False,
            }
        )
        at += 55.0 + rng.randrange(15)
        i += 1
    trace["tenants"].append(tenant("tenant-batch", 1.0, batch_events))
    # tenant-ml: a small long-running training footprint
    trace["tenants"].append(
        tenant(
            "tenant-ml",
            1.0,
            [
                {
                    "at": 8.0,
                    "kind": "submit",
                    "group": "trainer",
                    "count": 2,
                    "pod": {"cpu": "8", "memory": "16Gi"},
                    "replace": True,
                },
                # post-kill evaluation burst: this tenant's affinity also
                # pointed at the doomed replica, so its first post-kill
                # provisioning solve exercises failover from a second tenant
                {
                    "at": 140.0,
                    "kind": "submit",
                    "group": "eval",
                    "count": 2,
                    "pod": {"cpu": "8", "memory": "32Gi"},
                    "until": 210.0,
                    "replace": True,
                },
            ],
        )
    )
    return trace


def crash_churn(rng: Random) -> dict:
    """The crash-consistency gauntlet: service + wave churn (launches,
    binds, consolidation, an interruption) with the OPERATOR killed at all
    three journal barrier classes mid-run, against an ambiguous cloud
    (creates that land but whose acks are lost). Each kill cold-restarts
    the operator from the on-disk journal: the replacement waits out the
    dead incumbent's lease, replays pending intents — adopting
    acknowledged launches by idempotency key, rolling back in-flight
    disruption — and the run must end with zero double-launched NodeClaims
    and zero leaked instances. Each crash is armed shortly BEFORE a demand
    wave so the kill lands on that wave's intent flow; the last crash
    lands well over 200s before the end so GC's 2-minute sweep reaps
    anything recovery orphaned."""
    duration = 600.0
    trace = _base("crash-churn", duration=duration)
    trace["faults"] = {
        # the ambiguous failure the idempotency key exists for: the create
        # LANDS but the response is lost; the journaled retry must converge
        # on the instance already launched, never a second one
        "ack_then_raise_rate": 0.15,
        "launch_failure_rate": 0.1,
    }

    def wave(i: int, at: float, until: float) -> dict:
        return {
            "at": at,
            "kind": "submit",
            "group": f"wave-{i}",
            "count": 3 + rng.randrange(2),
            # big enough that a wave can't bind onto existing headroom:
            # every wave forces fresh launch intents for the kill to land on
            "pod": {"cpu": "3", "memory": "4Gi"},
            "until": until,
            "replace": True,
        }

    trace["events"] = [
        {
            "at": 4.0,
            "kind": "submit",
            "group": "svc",
            "count": 3 + rng.randrange(3),
            "pod": {"cpu": "2", "memory": "2Gi"},
            "replace": True,
        },
        # killed after an intent is durable but before its effect reaches
        # the cloud: recovery finds no instance, the claim relaunches
        {"at": 38.0, "kind": "operator-crash",
         "barrier": "post-intent-pre-effect"},
        wave(0, 40.0, 160.0),
        # killed after the cloud acked a launch but before the done record:
        # the adoption path — recovery finds the instance by idempotency key
        {"at": 118.0, "kind": "operator-crash",
         "barrier": "post-effect-pre-done", "action": "nodeclaim.launch"},
        wave(1, 120.0, 260.0),
        {"at": 200.0, "kind": "interrupt", "count": 1, "mode": "graceful"},
        # killed before the intent is even written: nothing journaled for
        # that action; everything else pending still recovers
        {"at": 208.0, "kind": "operator-crash", "barrier": "pre-intent"},
        wave(2, 210.0, 330.0),
    ]
    return trace


def sustained_churn(rng: Random) -> dict:
    """The steady-state the incremental delta solver exists for: a large,
    SHAPE-STABLE service footprint with continuous ~1% replace-churn and a
    diurnal arrival envelope. Every churn pod is the same shape and size as
    the base fleet — the delta encode re-encodes nothing after the first
    pass (all arrivals content-hit the row cache), and because the fused
    FFD scan sorts by size, uniform arrivals always extend the previous pod
    order as an exact suffix, keeping the warm scan-resume path engaged
    pass after pass. Churn arrives as small short-lived groups at a steady
    cadence (sinusoidally modulated: day peak, night trough) so every tick
    has a perturbed frontier but the cluster-scale state never rebuilds.
    No faults: with --delta-solve on the decisions must stay byte-identical
    to --delta-solve off, and the CI churn-smoke job diffs exactly that."""
    duration = 480.0
    trace = _base("sustained-churn", duration=duration, tick=2.0)
    # one uniform pod shape for base AND churn: shape-stability is the
    # point — warm resume requires arrivals that don't re-sort the stream
    pod = {"cpu": "1", "memory": "2Gi"}
    events = [
        {
            "at": 4.0,
            "kind": "submit",
            "group": "base",
            "count": 40 + rng.randrange(9),
            "pod": dict(pod),
            "replace": True,
        }
    ]
    # continuous churn: a short-lived group every ~12s, 1-2 pods each —
    # about 1% of the base footprint in flight per tick, modulated by a
    # full diurnal cycle across the trace
    at, i = 20.0, 0
    while at < duration - 90.0:
        phase = 2.0 * math.pi * (at / duration)
        level = 0.5 * (1.0 - math.cos(phase))  # 0 at edges, 1 mid-trace
        count = 1 + (1 if rng.random() < level else 0)
        events.append(
            {
                "at": round(at, 3),
                "kind": "submit",
                "group": f"churn-{i}",
                "count": count,
                "pod": dict(pod),
                "until": round(at + 50.0 + rng.randrange(25), 3),
                "replace": True,
            }
        )
        at += 10.0 + rng.randrange(5)
        i += 1
    trace["events"] = sorted(events, key=lambda e: e["at"])
    return trace


def capacity_pressure(rng: Random) -> dict:
    """The /debug/explain fixture: a limits-capped single pool under more
    demand than it may hold, plus two deliberately unsatisfiable pods whose
    eliminating stage is exact and distinct — a giant pod no instance type
    can fit (resources) and a pod pinned to a zone no offering serves
    (offerings). Fillers saturate the cpu limit so their overflow pends on
    limits, then drain at t=60 — headroom returns, and the unsatisfiable
    pods re-solve to their TRUE stages for the rest of the run (an
    exhausted pool eliminates everything at the limits stage, which would
    mask them). No faults: the triage table, the per-stage elimination
    counters, and the ledger digest are pure functions of the seed."""
    trace = _base("capacity-pressure", duration=180.0)
    # pin the pool to 4-cpu boxes and cap it at 12 cpu (3 nodes): a 3-cpu
    # filler owns a node, so any filler past the third pends on limits
    trace["nodepools"][0]["requirements"] = [
        {
            "key": "karpenter.kwok.sh/instance-size",
            "operator": "In",
            "values": ["4x"],
        }
    ]
    trace["nodepools"][0]["limits"] = {"cpu": "12"}
    trace["events"] = [
        {
            "at": 4.0,
            "kind": "submit",
            "group": "filler",
            "count": 5 + rng.randrange(2),
            "pod": {"cpu": "3", "memory": "2Gi"},
            "until": 60.0,
            "replace": True,
        },
        # no 4x instance type holds 64 cpu: every nodepool eliminates this
        # pod at the resources stage, forever
        {
            "at": 8.0,
            "kind": "submit",
            "group": "giant",
            "count": 1,
            "pod": {"cpu": "64", "memory": "4Gi"},
            "replace": True,
        },
        # no offering serves this zone: eliminated at the offerings stage
        {
            "at": 8.0,
            "kind": "submit",
            "group": "lost-zone",
            "count": 1,
            "pod": {"cpu": "1", "memory": "1Gi", "zone": "kwok-zone-9"},
            "replace": True,
        },
    ]
    return trace


def flaky_cloud(rng: Random) -> dict:
    """Steady demand against a misbehaving cloud: probabilistic launch
    failures, occasional capacity errors, API latency, a solver shedding
    part of its load, and a scheduled FULL cloud-API outage — the
    graceful-degradation gauntlet. The outage (with an interruption inside
    it forcing cloud deletes) drives the operator's circuit breaker through
    closed → open → half-open → closed and exercises per-item reconcile
    backoff, all in virtual time."""
    trace = _base("flaky-cloud", duration=360.0)
    trace["faults"] = {
        "launch_failure_rate": 0.3,
        "insufficient_capacity_rate": 0.1,
        "api_latency": 0.2,
        "api_jitter": 0.3,
        "solver_rejection_rate": 0.25,
        # long enough that the first half-open probe (default 30s cooldown)
        # fails and re-opens the breaker before recovery closes it
        "outages": [{"at": 150.0, "duration": 50.0}],
    }
    trace["events"] = [
        {
            "at": 4.0,
            "kind": "submit",
            "group": "svc",
            "count": 4 + rng.randrange(3),
            "pod": {"cpu": "2", "memory": "2Gi"},
            "replace": True,
        },
        # a graceful interruption mid-outage: its finalizer needs a cloud
        # delete, which fails until the breaker recovers — the per-item
        # backoff path for deletes
        {"at": 160.0, "kind": "interrupt", "count": 1, "mode": "graceful"},
    ]
    return trace
