"""Fault-injection layer: wrappers around the CloudProvider and the solverd
client plus scheduled-interruption executors.

All randomness comes from seeded ``random.Random`` streams owned by the
harness, so fault sequences replay exactly. Wrappers report every injection
through an ``on_fault`` callback that the harness routes into the event log
— faults are part of the scenario's observable record, not hidden state.
"""

from __future__ import annotations

from random import Random
from typing import Callable, Optional

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    CreateError,
    InsufficientCapacityError,
)
from karpenter_tpu.solverd import QueueFullError, SolverClient
from karpenter_tpu.utils.clock import Clock

OnFault = Callable[..., None]


def _noop_on_fault(ev: str, **fields) -> None:
    pass


class CloudOutageError(Exception):
    """Total cloud-API outage: create/delete fail untyped, the way a real
    region event looks to a controller. Deliberately NOT one of the typed
    domain errors — the lifecycle controller does not catch it, so it
    propagates to the reconciler harness (per-item backoff) and counts as
    a retryable failure for the circuit breaker."""


class FaultyCloudProvider(CloudProvider):
    """Wraps any CloudProvider with probabilistic launch failures, API
    latency, and scheduled full-API outage windows. Latency advances
    VIRTUAL time (clock.sleep) — under the simulator's FakeClock the whole
    control loop experiences a slow cloud API without any wall-clock
    cost."""

    def __init__(
        self,
        inner: CloudProvider,
        rng: Random,
        clock: Clock,
        launch_failure_rate: float = 0.0,
        insufficient_capacity_rate: float = 0.0,
        ack_then_raise_rate: float = 0.0,
        api_latency: float = 0.0,
        api_jitter: float = 0.0,
        outages: Optional[list[tuple[float, float]]] = None,
        on_fault: Optional[OnFault] = None,
    ):
        self.inner = inner
        self.rng = rng
        self.clock = clock
        self.launch_failure_rate = launch_failure_rate
        self.insufficient_capacity_rate = insufficient_capacity_rate
        self.ack_then_raise_rate = ack_then_raise_rate
        self.api_latency = api_latency
        self.api_jitter = api_jitter
        # absolute virtual-time [start, end) windows where EVERY
        # create/delete raises CloudOutageError
        self.outages = list(outages or [])
        self.on_fault = on_fault or _noop_on_fault
        self.launch_failures = 0
        self.capacity_errors = 0
        self.ack_then_raise_failures = 0
        self.outage_failures = 0

    def _lag(self) -> None:
        if self.api_latency <= 0 and self.api_jitter <= 0:
            return
        self.clock.sleep(self.api_latency + self.api_jitter * self.rng.random())

    def _outage(self, op: str, node_claim) -> None:
        now = self.clock.now()
        if any(start <= now < end for start, end in self.outages):
            self.outage_failures += 1
            self.on_fault(
                "fault-outage", op=op, nodeclaim=node_claim.metadata.name
            )
            raise CloudOutageError(f"sim: injected cloud outage ({op})")

    def create(self, node_claim):
        self._lag()
        self._outage("create", node_claim)
        roll = self.rng.random()
        if roll < self.launch_failure_rate:
            self.launch_failures += 1
            self.on_fault("fault-launch", nodeclaim=node_claim.metadata.name)
            raise CreateError(
                "sim: injected launch failure",
                condition_reason="SimInjectedFault",
            )
        if roll < self.launch_failure_rate + self.insufficient_capacity_rate:
            self.capacity_errors += 1
            self.on_fault("fault-ice", nodeclaim=node_claim.metadata.name)
            raise InsufficientCapacityError("sim: injected capacity shortage")
        threshold = (
            self.launch_failure_rate
            + self.insufficient_capacity_rate
            + self.ack_then_raise_rate
        )
        if roll < threshold:
            # the ambiguous failure: the cloud API acknowledges — the
            # instance MATERIALIZES — but the response is lost. A third
            # band of the same single roll, so rate 0 keeps existing
            # scenario digests byte-identical. The retry must converge via
            # the launch idempotency key, never a second instance.
            self.inner.create(node_claim)
            self.ack_then_raise_failures += 1
            self.on_fault("fault-ack-raise", nodeclaim=node_claim.metadata.name)
            raise CreateError(
                "sim: injected ambiguous ack (create landed, response lost)",
                condition_reason="SimAmbiguousAck",
            )
        return self.inner.create(node_claim)

    def delete(self, node_claim):
        self._lag()
        self._outage("delete", node_claim)
        return self.inner.delete(node_claim)

    def get(self, provider_id: str):
        return self.inner.get(provider_id)

    def list(self):
        return self.inner.list()

    def get_instance_types(self, node_pool):
        return self.inner.get_instance_types(node_pool)

    def is_drifted(self, node_claim) -> str:
        return self.inner.is_drifted(node_claim)

    def repair_policies(self):
        return self.inner.repair_policies()

    def name(self) -> str:
        return self.inner.name()

    def __getattr__(self, attr):
        # tick(), reclaim(), honor_overlays... pass through to the wrapped
        # provider so the operator sees the full surface
        if attr == "inner":
            raise AttributeError(attr)
        return getattr(self.inner, attr)


class FlakySolverClient(SolverClient):
    """Wraps the provisioner's solverd client with a probabilistic
    rejection storm — the degradation path a saturated (or restarting)
    solver daemon inflicts on its controllers."""

    transport = "flaky"

    def __init__(
        self,
        inner: SolverClient,
        rng: Random,
        rejection_rate: float = 0.0,
        on_fault: Optional[OnFault] = None,
    ):
        self.inner = inner
        self.rng = rng
        self.rejection_rate = rejection_rate
        self.on_fault = on_fault or _noop_on_fault
        self.rejections = 0

    def solve(self, kind, scheduler, pods, timeout=None, deadline=None,
              request_id=None, tenant=None):
        if self.rng.random() < self.rejection_rate:
            self.rejections += 1
            self.on_fault("fault-solver-reject", kind=kind, pods=len(list(pods)))
            raise QueueFullError("sim: injected rejection storm")
        return self.inner.solve(
            kind, scheduler, pods, timeout=timeout, deadline=deadline,
            request_id=request_id, tenant=tenant,
        )

    def stats(self) -> dict:
        stats = dict(self.inner.stats())
        stats["injected_rejections"] = self.rejections
        return stats

    def close(self) -> None:
        self.inner.close()


# -- scheduled interruptions --------------------------------------------------


def interrupt(
    store,
    provider,
    rng: Random,
    count: int = 1,
    mode: str = "graceful",
    capacity_type: Optional[str] = None,
    on_fault: Optional[OnFault] = None,
) -> int:
    """Interrupt up to ``count`` launched instances.

    graceful — the two-minute spot interruption notice: delete the
    NodeClaim so the normal drain → terminate → replace pipeline runs
    (what the interruption controller does on an SQS notice).

    reclaim — the cloud takes the capacity back out-of-band: the instance
    vanishes from the provider (kwok ``reclaim``) and its Node object
    drops out of the cluster; the GC controller later reaps the orphaned
    claim and the provisioner replaces the lost capacity.

    Victims are drawn deterministically (name-sorted, seeded rng) from
    launched claims matching the capacity-type filter. Returns the number
    of instances actually interrupted."""
    on_fault = on_fault or _noop_on_fault
    claims = [
        c
        for c in store.list("NodeClaim")
        if c.status.provider_id
        and c.metadata.deletion_timestamp is None
        and (
            capacity_type is None
            or c.metadata.labels.get(wk.CAPACITY_TYPE_LABEL_KEY) == capacity_type
        )
    ]
    claims.sort(key=lambda c: c.metadata.name)
    hit = 0
    for _ in range(min(count, len(claims))):
        victim = claims.pop(rng.randrange(len(claims)))
        if mode == "reclaim":
            if not provider.reclaim(victim.status.provider_id):
                continue
            # the node drops off the cluster with the instance
            for node in store.list(
                "Node",
                predicate=lambda n: n.spec.provider_id == victim.status.provider_id,
            ):
                node.metadata.finalizers = []
                store.delete(node)
            on_fault(
                "fault-reclaim",
                nodeclaim=victim.metadata.name,
                provider_id=victim.status.provider_id,
            )
        else:
            store.delete(victim)
            on_fault(
                "fault-interrupt",
                nodeclaim=victim.metadata.name,
                provider_id=victim.status.provider_id,
            )
        hit += 1
    return hit
