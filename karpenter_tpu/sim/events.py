"""Simulator event log: an append-only, canonically-serialized record of
everything observable that happened during a run.

The log is the determinism contract: two runs of the same scenario with the
same seed must produce byte-identical logs, so the digest (sha256 over the
canonical JSON line of every entry) is a regression-diffable fingerprint of
end-to-end behavior. Anything nondeterministic (wall-clock timestamps, host
metrics, object ids outside the seeded uid source) must stay OUT of entries.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Iterator


def canonical(entry: dict) -> str:
    """One entry as its canonical JSON line (sorted keys, no whitespace
    variance, explicit separators)."""
    return json.dumps(entry, sort_keys=True, separators=(",", ":"))


class EventLog:
    def __init__(self) -> None:
        self._entries: list[dict] = []
        self._hash = hashlib.sha256()

    def append(self, t: float, ev: str, **fields: Any) -> dict:
        entry = {"t": round(t, 6), "ev": ev}
        entry.update(fields)
        self._entries.append(entry)
        self._hash.update(canonical(entry).encode())
        self._hash.update(b"\n")
        return entry

    def digest(self) -> str:
        return "sha256:" + self._hash.hexdigest()

    def __len__(self) -> int:
        return len(self._entries)

    def __iter__(self) -> Iterator[dict]:
        return iter(self._entries)

    def entries(self, ev: str | None = None) -> list[dict]:
        if ev is None:
            return list(self._entries)
        return [e for e in self._entries if e["ev"] == ev]

    def to_jsonl(self) -> str:
        return "\n".join(canonical(e) for e in self._entries) + (
            "\n" if self._entries else ""
        )
