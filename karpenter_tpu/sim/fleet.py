"""Multi-tenant fleet simulation: N tenant clusters (each a full, real
operator cell — store, kwok cloud, every controller) sharing one solverd
replica pool on one virtual clock.

This is the deterministic harness for the solverd fleet's availability
story: every tenant's FleetClient routes by (tenant, catalog) affinity over
the shared pool, a `kill` event makes a replica vanish the way SIGKILL does
(connections refused, no drain, no goodbye — modeled at the transport
boundary, which is all a client can ever observe of a killed process), and
the run must recover deterministically: breakers open, routing converges on
the survivors, every replayed request id dedups, and no tenant's pods are
left unbound.

The report is a pure function of (trace, seed): per-tenant cost/SLO/churn
reports, a fleet section (per-replica execution audits, per-tenant failover
counters, the zero-double-execute verdict), the process-global tracing and
kernel-observatory sections folded once at pool level, and a combined
event-log digest over the time-merged tenant + fleet streams.
"""

from __future__ import annotations

import math
from dataclasses import replace as dc_replace
from typing import Optional

from karpenter_tpu.operator.options import Options
from karpenter_tpu.sim import trace as tracemod
from karpenter_tpu.sim.events import EventLog
from karpenter_tpu.sim.harness import SimResult, Simulation, sim_globals
from karpenter_tpu.solverd import (
    FleetClient,
    InProcessClient,
    SolverService,
    TransportError,
)
from karpenter_tpu.utils.clock import FakeClock


class KillableReplica(InProcessClient):
    """An in-process pool replica that can be killed mid-run. A killed
    replica answers every call the way a SIGKILLed daemon answers a socket
    client: connection refused, i.e. a typed retryable TransportError —
    the FleetClient's breaker and failover path see exactly what they
    would see in production."""

    def __init__(self, replica_id: str, service: SolverService):
        super().__init__(service)
        self.replica_id = replica_id
        self.dead = False

    def kill(self) -> None:
        self.dead = True
        # the process is gone: whatever the service held dies with it
        self.service.close()

    def _check(self) -> None:
        if self.dead:
            raise TransportError(
                f"connect {self.replica_id}: connection refused (killed)"
            )

    def encode(self, *args, **kwargs):
        # encode is host-side (client memory): it survives the kill; the
        # connection attempt in solve_prepared is what fails
        return super().encode(*args, **kwargs)

    def solve_prepared(self, prepared):
        self._check()
        return super().solve_prepared(prepared)

    def solve_many(self, *args, **kwargs):
        self._check()
        return super().solve_many(*args, **kwargs)

    def stats(self) -> dict:
        if self.dead:
            return {"transport": "inprocess", "error": "killed"}
        return super().stats()


class FleetSimulation:
    """Drive every tenant cell and the shared replica pool on one clock."""

    def __init__(
        self,
        trace: dict,
        seed: int,
        options: Optional[Options] = None,
        trace_export: Optional[str] = None,
    ):
        tracemod.validate(trace)
        if "fleet" not in trace:
            raise ValueError("FleetSimulation needs a trace with a 'fleet' section")
        self.trace = trace
        self.seed = seed
        self.clock = FakeClock()
        self.t0 = self.clock.now()
        self.fleet_log = EventLog()
        fleet = trace["fleet"]
        base = options or Options()

        tenant_weights = {
            t["name"]: float(t.get("weight", 1.0)) for t in trace["tenants"]
        }
        quota = int(fleet.get("tenant_quota", 0))
        self.services: list[SolverService] = []
        self.replicas: list[KillableReplica] = []
        for i in range(int(fleet["replicas"])):
            service = SolverService(
                clock=self.clock,
                max_queue_depth=base.solverd_queue_depth,
                tenant_quota=quota,
                tenant_weights=tenant_weights,
            )
            self.services.append(service)
            self.replicas.append(KillableReplica(f"replica-{i}", service))

        self.cells: list[Simulation] = []
        self.names: list[str] = []
        self.clients: dict[str, FleetClient] = {}
        for idx, spec in enumerate(trace["tenants"]):
            name = spec["name"]

            def solver_factory(cell, name=name):
                client = FleetClient(
                    [(r.replica_id, r) for r in self.replicas],
                    clock=self.clock,
                    tenant=name,
                    breaker_threshold=base.solverd_replica_breaker_threshold,
                    breaker_cooldown=base.solverd_replica_breaker_cooldown,
                )
                self.clients[name] = client
                return client

            cell = Simulation(
                spec["trace"],
                # distinct per-tenant seeds: three identical workloads would
                # otherwise draw identical fault/victim streams
                seed + idx,
                options=dc_replace(base, cluster_name=name),
                clock=self.clock,
                solver_factory=solver_factory,
                configure_tracer=False,
            )
            self.cells.append(cell)
            self.names.append(name)

        # the process-global tracer, configured ONCE after every cell's
        # Operator construction (each construction re-configures it):
        # deterministic mode so the combined span digest is a fingerprint
        from karpenter_tpu import tracing

        self.tracer = tracing.configure(
            clock=self.clock,
            sample_rate=1.0,
            deterministic=True,
            buffer_size=base.trace_buffer_size,
            jsonl_path=trace_export,
        )
        for cell in self.cells:
            cell.tracer = self.tracer
            cell.operator.tracer = self.tracer
        # pool-level SLO breach subscription: the cells each registered the
        # single-tenant "sim" subscriber at construction — keyed replace
        # swaps in ONE pool-level tap so every breach (tenant-tagged or
        # aggregate) lands exactly once, in the fleet stream
        from karpenter_tpu.observability import slo as slomod

        slomod.engine().subscribe(self._on_slo_breach, key="sim")
        self._kills = sorted(
            fleet.get("kills", []), key=lambda k: (k["at"], k["replica"])
        )
        self.killed: list[str] = []

    # -- the loop ------------------------------------------------------------

    def _rel(self, t: float) -> float:
        return t - self.t0

    def _on_slo_breach(self, breach) -> None:
        self.fleet_log.append(
            self._rel(breach.t),
            "slo-breach",
            objective=breach.objective,
            tenant=breach.tenant,
            window=breach.window,
            burn_rate=round(breach.burn_rate, 6),
            budget_remaining=round(breach.budget_remaining, 6),
        )

    def _apply_kills(self) -> None:
        while self._kills and self.t0 + self._kills[0]["at"] <= self.clock.now():
            kill = self._kills.pop(0)
            replica = self.replicas[int(kill["replica"])]
            replica.kill()
            self.killed.append(replica.replica_id)
            self.fleet_log.append(
                self._rel(self.clock.now()), "replica-kill",
                replica=replica.replica_id,
            )

    def run(self) -> SimResult:
        end = self.t0 + float(self.trace["duration"])
        with sim_globals(self.seed, self.clock):
            for cell in self.cells:
                cell.prepare()
            while True:
                t_kill = (
                    self.t0 + self._kills[0]["at"] if self._kills else math.inf
                )
                t_worker = self.clock.next_wakeup()
                t_next = min(
                    min(cell.next_due() for cell in self.cells),
                    t_kill,
                    math.inf if t_worker is None else t_worker,
                )
                if t_next > end:
                    break
                if t_next > self.clock.now():
                    self.clock.set_time(t_next)
                self._apply_kills()
                # fixed tenant order per step: the interleaving is part of
                # the determinism contract
                for cell in self.cells:
                    cell.step()
            report = self._finalize(end)
            self.tracer.close()
            merged = self._merged_log()
            report["event_log_digest"] = merged.digest()
            return SimResult(report=report, digest=merged.digest(), log=merged)

    # -- reporting -----------------------------------------------------------

    def _merged_log(self) -> EventLog:
        """One time-merged log over every tenant stream plus the fleet
        events, each entry stamped with its origin — the combined digest is
        the run's fingerprint. Ties break by stream order (fleet first,
        then tenants in trace order) and intra-stream position — both
        deterministic."""
        streams = [("fleet", self.fleet_log)] + [
            (name, cell.log) for name, cell in zip(self.names, self.cells)
        ]
        tagged = []
        for order, (origin, log) in enumerate(streams):
            for position, entry in enumerate(log):
                tagged.append((entry["t"], order, position, origin, entry))
        tagged.sort(key=lambda item: item[:3])
        merged = EventLog()
        for t, _order, _position, origin, entry in tagged:
            fields = {
                k: v for k, v in entry.items() if k not in ("t", "ev")
            }
            if origin != "fleet":
                fields["tenant"] = origin
            merged.append(t, entry["ev"], **fields)
        return merged

    def _double_executed(self) -> dict:
        """The zero-double-execute audit: a request id executed twice on one
        replica means the dedup failed; one executed on two replicas means
        a replay re-ran a solve that had already run (possible only when a
        reply is lost AFTER execution — the at-least-once edge the clean
        SIGKILL never produces). Both must be zero here."""
        same_replica = 0
        seen: dict[str, int] = {}
        cross_replica = 0
        overflow = False
        for service in self.services:
            overflow = overflow or service.executed_ids_overflow
            for rid, count in service.executed_ids.items():
                if count > 1:
                    same_replica += count - 1
                if rid in seen:
                    cross_replica += 1
                seen[rid] = seen.get(rid, 0) + 1
        return {
            "same_replica": same_replica,
            "cross_replica": cross_replica,
            "total": same_replica + cross_replica,
            "audit_overflow": overflow,
        }

    def _finalize(self, end: float) -> dict:
        from karpenter_tpu.observability import flight as flightmod
        from karpenter_tpu.observability import kernels as kobs
        from karpenter_tpu.observability import slo as slomod

        engine = slomod.engine()
        tenants = {}
        for name, cell in zip(self.names, self.cells):
            tenants[name] = cell.finalize(end, process_sections=False)
            # the per-tenant SLO section: this tenant's burn/budget state
            # for every objective its tag appeared on — the shape the
            # ~100-cell macrobench scales to
            tenants[name]["slo"]["objectives"] = engine.tenant_section(name)
        replicas = []
        for service, replica in zip(self.services, self.replicas):
            replicas.append(
                {
                    "id": replica.replica_id,
                    "killed": replica.dead,
                    "requests": service.requests,
                    "executed": service.executed,
                    "batches": service.batches,
                    "rejected": service.rejected,
                    "deduped": service.deduped,
                    "unique_request_ids": len(service.executed_ids),
                }
            )
        clients = {}
        for name in self.names:
            client = self.clients.get(name)
            if client is None:
                continue
            stats = client.stats()
            clients[name] = {
                "failovers": stats["failovers"],
                "replays": stats["replays"],
                "draining_failovers": stats["draining_failovers"],
                "healthy_replicas": stats["healthy_replicas"],
                "solves_by_replica": {
                    r["id"]: r["solves"] for r in stats["replicas"]
                },
                "breakers": {
                    r["id"]: r["breaker"] for r in stats["replicas"]
                },
            }
        report = {
            "report_version": 1,
            "scenario": self.trace.get("name", ""),
            "seed": self.seed,
            "virtual_duration_s": round(end - self.t0, 6),
            "tenants": tenants,
            "fleet": {
                "replicas": replicas,
                "replica_kills": list(self.killed),
                "clients": clients,
                "double_executed": self._double_executed(),
            },
            # process-global sections folded ONCE at pool level: the span
            # digest covers every tenant's spans, the kernel section the
            # pool's dispatch counts (the surviving replica's steady
            # recompiles must stay 0 through the kill)
            "tracing": {
                "span_digest": self.tracer.digest.digest(),
                "spans": self.tracer.digest.count,
            },
            "kernels": kobs.registry().report(
                self.cells[0]._kernels_base if self.cells else None
            ),
            # pool-level SLO verdict (per-tenant attribution inside) and
            # the flight recorder's ring/bundle digests — one engine, one
            # blackbox, folded once like the tracing section
            "slo": engine.report(),
            "flight": flightmod.recorder().report(),
        }
        # the efficiency observatory folds once at pool level too (its
        # steady-batch counters are process-global, like the kernel
        # counts); outside the kernels digest, deterministic for
        # host-only scenarios exactly like the single-cell report
        from karpenter_tpu.observability import efficiency as effmod

        report["kernels"]["efficiency"] = effmod.report_section(
            self.cells[0]._eff_base if self.cells else None
        )
        return report


def run_fleet_scenario(
    trace: dict,
    seed: int,
    options: Optional[Options] = None,
    trace_export: Optional[str] = None,
) -> SimResult:
    return FleetSimulation(
        trace, seed, options=options, trace_export=trace_export
    ).run()
