"""karpenter_tpu.sim — deterministic trace-driven cluster simulator.

Replays a workload trace through the REAL control loop (provisioner →
solverd → kwok create → binding → disruption → termination) on virtual
time, with fault injection and cost/SLO accounting. See
docs/ARCHITECTURE.md ("Simulator") and `python -m karpenter_tpu.sim --list`.
"""

from karpenter_tpu.sim.events import EventLog
from karpenter_tpu.sim.faults import (
    FaultyCloudProvider,
    FlakySolverClient,
    interrupt,
)
from karpenter_tpu.sim.harness import SimResult, Simulation, build_pod, run_scenario

__all__ = [
    "EventLog",
    "FaultyCloudProvider",
    "FlakySolverClient",
    "SimResult",
    "Simulation",
    "build_pod",
    "interrupt",
    "run_scenario",
]
