"""The simulator: a deterministic, trace-driven, virtual-time event loop
around the REAL operator — every controller, the solverd client, and the
kwok cloud provider, exactly as `python -m karpenter_tpu` wires them.

Virtual time: a single FakeClock drives everything. The loop never sleeps;
it jumps the clock to the next due event (trace event, controller tick, or
a blocked worker thread's wakeup) and runs one cooperative operator pass
when a tick is due. A 400-virtual-second scenario runs in well under a
wall-clock second.

Determinism: the trace is a pure function of the seed, a seeded uid source
replaces uuid4 for object names, fault coin-flips and victim selection use
dedicated seeded streams, and the operator loop itself is single-threaded —
so identical seeds yield byte-identical event logs (compare digests).

The harness also plays the two cluster roles the framework leaves external:
a ReplicaSet stand-in (workload groups resubmit pods that were evicted or
lost, until the group's completion time) and a pod-GC stand-in (pods bound
to a Node that vanished out-of-band are deleted, then resubmitted by their
group).
"""

from __future__ import annotations

import math
import shutil
import tempfile
from contextlib import contextmanager
from dataclasses import dataclass, field
from random import Random
from typing import Optional

from karpenter_tpu.apis import core as apicore
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Condition,
    Container,
    LabelSelector,
    ObjectMeta,
    Pod,
    PodSpec,
    TopologySpreadConstraint,
)
from karpenter_tpu.apis.nodepool import NodePool
from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.runtime.journal import IDEMPOTENCY_ANNOTATION, OperatorCrash
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.sim import trace as tracemod
from karpenter_tpu.sim.accounting import Accountant, node_facts
from karpenter_tpu.sim.events import EventLog
from karpenter_tpu.sim.faults import FaultyCloudProvider, FlakySolverClient, interrupt
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.resources import parse_resource_list

GROUP_LABEL = "sim.kwok.sh/group"


@dataclass
class SimResult:
    report: dict
    digest: str
    log: EventLog


@dataclass
class _Group:
    name: str
    desired: int
    pod_spec: dict
    until: Optional[float]  # absolute virtual time; None = end of trace
    replace: bool
    serial: int = 0  # replacement counter (deterministic replica names)
    done: bool = False


def build_pod(name: str, group: str, spec: dict) -> Pod:
    """Trace pod template -> an unschedulable Pod (the shape the reference's
    test.UnschedulablePod builder produces, so it is provisionable)."""
    node_selector: dict[str, str] = {}
    if spec.get("capacity_type"):
        node_selector[wk.CAPACITY_TYPE_LABEL_KEY] = spec["capacity_type"]
    if spec.get("zone"):
        node_selector[wk.LABEL_TOPOLOGY_ZONE] = spec["zone"]
    if spec.get("arch"):
        node_selector[wk.LABEL_ARCH] = spec["arch"]
    labels = {GROUP_LABEL: group}
    labels.update(spec.get("labels", {}))
    requests = {
        "cpu": str(spec.get("cpu", "1")),
        "memory": str(spec.get("memory", "1Gi")),
    }
    pod = Pod(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=PodSpec(
            node_selector=node_selector,
            containers=[Container(requests=parse_resource_list(requests))],
        ),
    )
    if spec.get("spread") == "zone":
        pod.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=wk.LABEL_TOPOLOGY_ZONE,
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(match_labels={GROUP_LABEL: group}),
            )
        ]
    pod.status.conditions.append(
        Condition(type="PodScheduled", status="False", reason="Unschedulable")
    )
    return pod


# Pinned device RTT for _use_device routing (ops/catalog.device_rtt_s):
# the measured RTT is wall-clock and machine-dependent, so borderline
# cubes could route host on one run and device on the next — and
# report["kernels"] dispatch counts would not be a pure function of
# (scenario, seed). 100µs sits at the co-located-chip scale: small
# cubes keep the exact host twins, large cubes keep the device.
PINNED_RTT_S = 100e-6


@contextmanager
def sim_globals(seed: int, clock: FakeClock):
    """The process-global discipline every deterministic run needs, held
    for exactly the run's duration: seeded uid source, blocking FakeClock
    sleeps, a fresh kernel-observatory warmup window, hermetic engines
    (a content-cached engine from an earlier sim would already be warm and
    its warmup dispatches would not repeat), and the pinned device RTT.
    One `with` block serves a single-tenant Simulation.run() or a whole
    multi-tenant FleetSimulation — the globals are process-wide either
    way, so they must be entered once per run, never per cell."""
    from karpenter_tpu.controllers.provisioning import provisioner as provmod
    from karpenter_tpu.observability import flight as flightmod
    from karpenter_tpu.observability import kernels as kobs
    from karpenter_tpu.observability import slo as slomod
    from karpenter_tpu.ops import catalog as catmod

    from karpenter_tpu.observability import efficiency as effmod

    apicore.set_uid_source(Random(f"{seed}:uids"))
    clock.enable_blocking_sleep()
    kobs.registry().unseal()
    # fresh SLO/flight state per run (specs, sources, and subscribers were
    # wired at operator construction and survive): burn-rate series,
    # breach history, frames, and bundle sequence all restart at zero so
    # report["slo"]/report["flight"] are pure functions of (scenario, seed)
    slomod.engine().reset()
    flightmod.recorder().reset()
    # fresh provenance ledger per run (mode/capacity survive — they were
    # configured at operator construction): ring, staging, and fused-decline
    # taxonomy restart at zero so report["explain"] and its digest are pure
    # functions of (scenario, seed)
    from karpenter_tpu.observability import explain as explainmod

    explainmod.recorder().reset()
    # device-profiler sequence + cooldowns restart so breach-armed capture
    # names (recorded in flight bundle contexts) are a pure function of
    # the run, not of process history
    effmod.profiler().reset()
    provmod._ENGINE_CONTENT_CACHE.clear()
    pinned_prev = catmod.PINNED_RTT
    catmod.PINNED_RTT = PINNED_RTT_S
    try:
        yield
    finally:
        catmod.PINNED_RTT = pinned_prev
        apicore.set_uid_source(None)
        clock.disable_blocking_sleep()


class Simulation:
    def __init__(
        self,
        trace: dict,
        seed: int,
        options: Optional[Options] = None,
        registration_delay: float = 2.0,
        trace_export: Optional[str] = None,
        clock: Optional[FakeClock] = None,
        solver_factory=None,
        configure_tracer: bool = True,
    ):
        tracemod.validate(trace)
        self.trace = trace
        self.seed = seed
        self.clock = clock if clock is not None else FakeClock()
        self.t0 = self.clock.now()
        self.log = EventLog()
        self.store = Store(clock=self.clock)
        self.kwok = KwokCloudProvider(
            self.store, self.clock, registration_delay=registration_delay
        )
        faults = trace.get("faults", {}) or {}
        self.provider = FaultyCloudProvider(
            self.kwok,
            rng=Random(f"{seed}:cloud-faults"),
            clock=self.clock,
            launch_failure_rate=faults.get("launch_failure_rate", 0.0),
            insufficient_capacity_rate=faults.get("insufficient_capacity_rate", 0.0),
            ack_then_raise_rate=faults.get("ack_then_raise_rate", 0.0),
            api_latency=faults.get("api_latency", 0.0),
            api_jitter=faults.get("api_jitter", 0.0),
            outages=[
                (self.t0 + float(o["at"]), self.t0 + float(o["at"]) + float(o["duration"]))
                for o in faults.get("outages", [])
            ],
            on_fault=self._on_fault,
        )
        self.options = options if options is not None else Options()
        # crash-injection scenarios need a REAL on-disk journal: the
        # cold-restarted operator recovers by re-reading the same files the
        # dead one fsync'd, so an in-memory journal would make the exercise
        # vacuous. A tempdir is provisioned only when the trace actually
        # crashes the operator and no --journal-dir was given; finalize()
        # removes it.
        self._journal_tmpdir = None
        if not self.options.journal_dir and any(
            e.get("kind") == "operator-crash" for e in trace.get("events", [])
        ):
            self._journal_tmpdir = tempfile.mkdtemp(prefix="ktpu-journal-")
            self.options.journal_dir = self._journal_tmpdir
        self.operator = Operator(
            self.store, self.provider, clock=self.clock, options=self.options
        )
        # a multi-tenant coordinator (sim/fleet.py) swaps the freshly built
        # in-process client for its shared replica pool BEFORE any fault
        # wrapping, so the flaky layer and the scenario see the pool
        self._solver_factory = solver_factory
        if solver_factory is not None:
            self.operator.provisioner.solver = solver_factory(self)
        # re-install the tracer the Operator just configured, in DETERMINISTIC
        # mode: full sampling (journeys and the span digest must be complete),
        # volatile wall-clock attrs dropped at export — so two same-seed runs
        # emit byte-identical span logs, and the digest below is a regression
        # fingerprint exactly like the event-log digest. (The tracer is
        # process-global: a multi-tenant coordinator configures it ONCE
        # after building every cell, so it passes configure_tracer=False.)
        from karpenter_tpu import tracing

        if configure_tracer:
            self.tracer = tracing.configure(
                clock=self.clock,
                sample_rate=1.0,
                deterministic=True,
                buffer_size=self.options.trace_buffer_size,
                jsonl_path=trace_export,
            )
        else:
            self.tracer = tracing.tracer()
        self.operator.tracer = self.tracer
        # the operator's cloud-provider circuit breaker is part of the
        # scenario's observable record: every transition lands in the event
        # log (deterministic — virtual time, seeded faults), and the
        # Accountant folds them into report["breaker"]
        self.operator.breaker.subscribe(
            lambda old, new: self.log.append(
                self._rel(self.clock.now()), "breaker", **{"from": old, "to": new}
            )
        )
        # SLO breaches are part of the scenario's observable record: every
        # edge-triggered breach lands in the event log (deterministic —
        # burn rates over virtual time) exactly like breaker transitions.
        # Keyed replace: a multi-tenant coordinator overrides this with one
        # pool-level subscription after building its cells.
        from karpenter_tpu.observability import slo as slomod

        slomod.engine().subscribe(self._on_slo_breach, key="sim")
        # kept for solverd-restart: the rebuilt client must re-wrap with the
        # SAME flaky profile and the SAME rng stream (mid-stream — byte
        # determinism depends on continuing it, not reseeding)
        self._solver_rejection_rate = faults.get("solver_rejection_rate", 0.0)
        self._solver_fault_rng = Random(f"{seed}:solver-faults")
        if self._solver_rejection_rate > 0:
            self.operator.provisioner.solver = FlakySolverClient(
                self.operator.provisioner.solver,
                rng=self._solver_fault_rng,
                rejection_rate=self._solver_rejection_rate,
                on_fault=self._on_fault,
            )
        # ffd's solve counters are module globals that accumulate across
        # every sim in the process; snapshot them so the report carries
        # THIS run's deltas and stays reproducible run-over-run
        from karpenter_tpu.ops import ffd

        self._ffd_base = {
            "joint_sweeps": ffd.JOINT_SWEEPS,
            "device_solves": ffd.DEVICE_SOLVES,
            "device_fallbacks": ffd.DEVICE_FALLBACKS,
        }
        # incremental-delta residencies (ops/delta.py) are process-global
        # and keyed by engine identity; the solverd engine factory content-
        # caches engines, so a second in-process run would otherwise
        # warm-resume against state seeded by the PREVIOUS run. Drop them,
        # then snapshot the counters for this run's report delta.
        from karpenter_tpu.ops import delta as deltamod

        deltamod.invalidate_all("sim-run-start")
        self._delta_base = dict(deltamod.delta_counters())
        # kernel observatory: same delta discipline — report["kernels"] is
        # built from a counts_snapshot taken at run start (run() also
        # unseals, so this run's prewarm/first-batch dispatches land in the
        # warmup phase exactly like a cold process's would)
        from karpenter_tpu.observability import kernels as kobs

        self._kernels_base = kobs.registry().counts_snapshot()
        # consolidation frontier counters (methods.py): snapshot for
        # per-run deltas — rounds/probes/coalesced groups are scenario
        # facts and belong in the deterministic report surface
        self._frontier_base = self._frontier_snapshot()
        # AOT compile-service traffic (cache hits/misses, fresh compiles,
        # off-ladder dispatches): snapshotted so the report carries this
        # run's deltas; the section rides OUTSIDE the kernels digest — a
        # warm second run legitimately hits the cache a cold first run
        # missed, and that must not break report-digest equality
        from karpenter_tpu.aot import runtime as aotrt

        self._aot_base = aotrt.stats()
        # efficiency observatory (host-stall attribution + cost tables):
        # steady-batch counters are process-cumulative, so the report
        # section is a delta from run start, like the kernels section
        from karpenter_tpu.observability import efficiency as effmod

        self._eff_base = effmod.snapshot_base()
        self._victim_rng = Random(f"{seed}:victims")
        # crash-consistency ledger: counts accumulated across every injected
        # crash and every Operator.recover() replay, folded into
        # report["recovery"] for ALL runs (zeros on crash-free scenarios, so
        # same-seed digest equality is unconditional)
        self._recovery = {
            "crashes": 0,
            "replayed": 0,
            "adoptions": 0,
            "orphans": 0,
            "rolled_back": 0,
        }
        self.operator.on_recover = self._on_recover
        self._groups: dict[str, _Group] = {}
        self._known_nodes: set[str] = set()
        self._known_claims: set[str] = set()
        self._bound: set[str] = set()

    # -- event-log taps ------------------------------------------------------

    def _on_fault(self, ev: str, **fields) -> None:
        self.log.append(self._rel(self.clock.now()), ev, **fields)

    def _on_recover(self, stats: dict) -> None:
        self._recovery["replayed"] += stats.get("replayed", 0)
        self._recovery["adoptions"] += stats.get("adoptions", 0)
        self._recovery["orphans"] += stats.get("orphans", 0)
        self._recovery["rolled_back"] += stats.get("rolled_back", 0)
        # an all-zero recovery (every boot runs one — empty journal) stays
        # out of the log so crash-free scenario digests are untouched
        if any(stats.values()):
            self.log.append(
                self._rel(self.clock.now()), "operator-recovered", **stats
            )

    def _on_slo_breach(self, breach) -> None:
        self.log.append(
            self._rel(breach.t),
            "slo-breach",
            objective=breach.objective,
            tenant=breach.tenant,
            window=breach.window,
            burn_rate=round(breach.burn_rate, 6),
            budget_remaining=round(breach.budget_remaining, 6),
        )

    def _rel(self, t: float) -> float:
        return t - self.t0

    # -- the loop ------------------------------------------------------------

    # kept as a class attr for callers that referenced it here
    PINNED_RTT_S = PINNED_RTT_S

    def prepare(self) -> None:
        """Stage the run: create nodepools, arm the trace-event queue and
        the first controller tick. Split out of run() so a multi-tenant
        coordinator can prepare every cell before driving one shared
        clock."""
        for np_spec in self.trace.get("nodepools", [{"name": "workers"}]):
            self.store.create(self._nodepool(np_spec))
        self._events = list(self.trace["events"])
        self._next_pass = self.t0
        self._tick = float(self.trace.get("tick", 1.0))

    def next_due(self) -> float:
        """The next virtual time this cell needs the clock to reach: its
        next trace event or its next controller tick."""
        t_event = self.t0 + self._events[0]["at"] if self._events else math.inf
        return min(self._next_pass, t_event)

    def step(self) -> None:
        """Apply every due trace event, then run one operator pass if the
        tick is due — at the clock's CURRENT time (the caller owns time)."""
        while self._events and self.t0 + self._events[0]["at"] <= self.clock.now():
            self._apply(self._events.pop(0))
        if self.clock.now() >= self._next_pass:
            try:
                summary = self.operator.run_once()
            except OperatorCrash as crash:
                # the injected kill: the pass dies mid-flight at a journal
                # barrier; a cold operator replaces it on the same store +
                # journal dir and recovers on its first leader pass
                self._crash_restart(crash)
                summary = {}
            self._workloads()
            self._observe(summary)
            self._next_pass = self.clock.now() + self._tick

    def run(self) -> SimResult:
        end = self.t0 + float(self.trace["duration"])
        with sim_globals(self.seed, self.clock):
            self.prepare()
            while True:
                t_worker = self.clock.next_wakeup()
                t_next = min(
                    self.next_due(),
                    math.inf if t_worker is None else t_worker,
                )
                if t_next > end:
                    break
                if t_next > self.clock.now():
                    # virtual time jumps straight to the next due event —
                    # this is the "no sleeping" core of the simulator
                    self.clock.set_time(t_next)
                self.step()
            report = self.finalize(end)
            self.tracer.close()  # flush the JSONL export, if any
            return SimResult(report=report, digest=self.log.digest(), log=self.log)

    def finalize(self, end: float, process_sections: bool = True) -> dict:
        """Fold the run into its report and shut the operator down. The
        process-global sections (tracing digest, kernel observatory, AOT,
        frontier counters) are singletons — a multi-tenant coordinator
        passes process_sections=False per cell and folds them ONCE at pool
        level instead."""
        from karpenter_tpu.observability import kernels as kobs

        report = Accountant(self.kwok.instance_types, self.t0).report(
            self.log,
            end,
            scenario=self.trace.get("name", ""),
            seed=self.seed,
            solver_stats=self._solver_stats(),
        )
        # crash-consistency verdict — in EVERY report (zeros on crash-free
        # runs), inside the deterministic surface: counts from the injected
        # crashes and the recoveries they forced, plus the two invariants
        # the journal exists to hold. double_launches is kwok's per-key
        # materialization ledger (kept across deletes). orphans_leaked is
        # an end-of-run sweep: an acknowledged instance is leaked only if
        # NO claim owns it by provider id or by idempotency key — a claim
        # mid-retry (create acked, response lost on the final pass) still
        # owns its instance by key and will converge, so it doesn't count.
        claims = self.store.list("NodeClaim")
        store_pids = {c.status.provider_id for c in claims if c.status.provider_id}
        store_keys = {
            c.metadata.annotations.get(IDEMPOTENCY_ANNOTATION, "") for c in claims
        }
        report["recovery"] = {
            "crashes": self._recovery["crashes"],
            "replayed_intents": self._recovery["replayed"],
            "adoptions": self._recovery["adoptions"],
            "orphans_marked": self._recovery["orphans"],
            "rolled_back": self._recovery["rolled_back"],
            "double_launches": self.kwok.double_launches(),
            "orphans_leaked": sum(
                1
                for inst in self.kwok.list()
                if inst.status.provider_id not in store_pids
                and inst.metadata.annotations.get(IDEMPOTENCY_ANNOTATION, "")
                not in store_keys
            ),
        }
        self.operator.shutdown()
        if self._journal_tmpdir is not None:
            shutil.rmtree(self._journal_tmpdir, ignore_errors=True)
        if not process_sections:
            return report
        # fold the scheduling traces into the report: the span-log
        # digest (determinism fingerprint) and per-stage journey
        # p50/p99 over every pod that completed its journey
        report["tracing"] = {
            "span_digest": self.tracer.digest.digest(),
            "spans": self.tracer.digest.count,
            "journeys": self.tracer.journeys.stats(),
        }
        # the kernel observatory section: per-(kernel, shape bucket,
        # phase) dispatch count deltas + steady recompiles, digested —
        # byte-deterministic across same-seed runs under the pinned RTT;
        # walls and compile counts ride in its volatile appendix
        report["kernels"] = kobs.registry().report(self._kernels_base)
        # AOT compile-service deltas, deliberately OUTSIDE the digest
        # (cache hits are process/disk history, not scenario facts)
        from karpenter_tpu.aot import runtime as aotrt

        report["kernels"]["aot"] = aotrt.stats_delta(self._aot_base)
        # efficiency observatory, also OUTSIDE the digest (cost models and
        # measured walls are machine facts). Its deterministic half —
        # steady batch counts, dispatch counts, and the exact-1.0 fraction
        # of fully host-paced runs — still reproduces per seed, so
        # full-report equality holds on scenarios that never
        # device-dispatch under the pinned RTT.
        from karpenter_tpu.observability import efficiency as effmod

        report["kernels"]["efficiency"] = effmod.report_section(self._eff_base)
        # incremental-delta counters (warm/cold passes, rows reused vs
        # re-encoded, bytes re-encoded, self-check verdicts, invalidations
        # by reason): this run's deltas, OUTSIDE the digest like aot —
        # residency is process history (the engine factory content-caches
        # engines across runs), not a scenario fact. All zeros with
        # --delta-solve off, so existing digests are untouched.
        from karpenter_tpu.ops import delta as deltamod

        cur = deltamod.delta_counters()
        report["kernels"]["delta"] = {
            key: cur.get(key, 0) - self._delta_base.get(key, 0) for key in cur
        }
        # consolidation frontier search: this run's rounds/probes per
        # consolidation type plus the solverd frontier groups that
        # coalesced — deterministic (decision-path) facts
        snap = self._frontier_snapshot()
        report["frontier"] = {
            key: round(snap[key] - self._frontier_base[key], 6)
            for key in snap
        }
        # the SLO engine's verdict over the run — per-objective burn/budget
        # state, the breach stream, and its own digest — folded into the
        # accounting slo section; plus the flight recorder's ring/bundle
        # digests. Both are pure functions of (scenario, seed).
        from karpenter_tpu.observability import flight as flightmod
        from karpenter_tpu.observability import slo as slomod

        engine_report = slomod.engine().report()
        report["slo"]["objectives"] = engine_report["objectives"]
        report["slo"]["breaches"] = engine_report["breaches"]
        report["slo"]["breaches_total"] = engine_report["breaches_total"]
        report["slo"]["digest"] = engine_report["digest"]
        report["flight"] = flightmod.recorder().report()
        # the provenance ledger's verdict — per-stage elimination totals,
        # fused-decline taxonomy, and a sha256 digest over the canonical
        # ledger entries. Inside the deterministic surface: funnels carry
        # stages + error strings only (host/device parity-guaranteed), and
        # entry timestamps are virtual time.
        from karpenter_tpu.observability import explain as explainmod

        report["explain"] = explainmod.recorder().report()
        return report

    @staticmethod
    def _frontier_snapshot() -> dict:
        from karpenter_tpu.controllers.disruption import methods as dmethods
        from karpenter_tpu.solverd import coalescer as dcoal

        out = {}
        for ctype in ("multi", "single"):
            labels = {"consolidation_type": ctype}
            out[f"{ctype}_rounds"] = float(
                dmethods._FRONTIER_ROUNDS.count(labels)
            )
            out[f"{ctype}_probes"] = dmethods._FRONTIER_PROBES.value(labels)
        out["coalesced_groups"] = dcoal._FRONTIER_GROUPS.value()
        return out

    def _solver_stats(self) -> dict:
        stats = dict(self.operator.solver_stats())
        for key, base in self._ffd_base.items():
            if isinstance(stats.get(key), int):
                stats[key] -= base
        # wall-clock measurements stay on /debug/solverd but OUT of the
        # report: the report must be a pure function of (scenario, seed)
        stats.pop("last_batch_seconds", None)
        stats.pop("last_batch_host_stall", None)
        return stats

    # -- trace events --------------------------------------------------------

    def _nodepool(self, spec: dict) -> NodePool:
        from karpenter_tpu.apis.nodepool import Budget

        np_ = NodePool(metadata=ObjectMeta(name=spec["name"]))
        np_.spec.template.spec.requirements = list(spec.get("requirements", []))
        np_.spec.disruption.consolidate_after = spec.get("consolidate_after", 15.0)
        if spec.get("budgets"):
            # e.g. [{"nodes": "100%"}] — the default 10% budget caps
            # disruption at ONE node on small simulated fleets, which
            # forces every consolidation through the single-node path
            np_.spec.disruption.budgets = [Budget(**b) for b in spec["budgets"]]
        if spec.get("limits"):
            np_.spec.limits = parse_resource_list(spec["limits"])
        np_.set_condition("Ready", "True")
        self.log.append(self._rel(self.clock.now()), "nodepool", name=spec["name"])
        return np_

    def _apply(self, ev: dict) -> None:
        kind = ev["kind"]
        if kind == "submit":
            until = ev.get("until")
            group = _Group(
                name=ev["group"],
                desired=int(ev["count"]),
                pod_spec=dict(ev.get("pod", {})),
                until=None if until is None else self.t0 + float(until),
                replace=bool(ev.get("replace", False)),
            )
            self._groups[group.name] = group
            for i in range(group.desired):
                self._submit(group, f"{group.name}-{i}")
        elif kind == "interrupt":
            interrupt(
                self.store,
                self.provider,
                self._victim_rng,
                count=int(ev.get("count", 1)),
                mode=ev.get("mode", "graceful"),
                capacity_type=ev.get("capacity_type"),
                on_fault=self._on_fault,
            )
        elif kind == "solverd-restart":
            self._restart_solverd()
        elif kind == "operator-crash":
            # arm a one-shot kill at a named journal barrier; it fires on
            # the next matching intent/done, possibly several passes later
            self.operator.journal.arm_crash(
                ev.get("barrier", "post-intent-pre-effect"),
                action=ev.get("action"),
            )
        else:
            raise ValueError(f"unknown trace event kind {kind!r}")

    def _restart_solverd(self) -> None:
        """Restart the solver service mid-trace (the rolling-upgrade path
        ROADMAP item 2 hardens): the old client closes, engines and their
        device state are dropped (a restarted daemon holds none), and the
        next provisioning pass re-prewarms from scratch — against the
        persistent AOT executable cache when one is configured, which is
        exactly what the warm-start contract asserts stays fast."""
        from karpenter_tpu.controllers.provisioning import (
            provisioner as provmod,
        )
        from karpenter_tpu.observability import kernels as kobs
        from karpenter_tpu.solverd import build_solver

        prov = self.operator.provisioner
        try:
            prov.solver.close()
        except Exception:  # noqa: BLE001 — a dying daemon can't block the sim
            pass
        prov.solver = build_solver(self.operator.options, self.clock)
        # the scenario's fault profile survives the restart: re-wrap the
        # fresh client, continuing the established rng stream
        if self._solver_rejection_rate > 0:
            prov.solver = FlakySolverClient(
                prov.solver,
                rng=self._solver_fault_rng,
                rejection_rate=self._solver_rejection_rate,
                on_fault=self._on_fault,
            )
        # cold-engine discipline: the restarted daemon rebuilds engines from
        # shipped catalogs, so both engine cache levels drop
        provmod._ENGINE_CONTENT_CACHE.clear()
        # ... and holds no executables: the AOT table empties so the
        # re-prewarm actually drives the persistent-cache LOAD path (not the
        # already-loaded fast path), and — when the compile service is on —
        # the jit caches drop too, so a cacheless restart honestly repays
        # its compiles. Deterministic: warm_start records one dispatch per
        # bucket whichever of compile/load/already served it.
        from karpenter_tpu.aot import runtime as aotrt

        aotrt.clear_executables()
        if aotrt.enabled():
            try:
                import jax

                jax.clear_caches()
            except Exception:  # noqa: BLE001 — jax never imported: nothing to clear
                pass
        if prov.engine_factory is not None:
            prov.engine_factory = provmod.default_engine_factory(
                shard_devices=prov.options.solver_pod_shard_axis
            )
        # a restart reopens the warmup window: the re-prewarm (and the first
        # post-restart solve's residual compiles) are cold-start facts, not
        # steady-state recompiles
        prov._kernels_sealed = False
        prov._prewarm_traced = False
        kobs.registry().unseal()
        self.log.append(self._rel(self.clock.now()), "solverd-restart")

    def _crash_restart(self, crash: OperatorCrash) -> None:
        """Cold-restart the operator after an injected crash. The dying
        process gets NO orderly shutdown — only what the OS does for it
        (file handles drop; every journal frame was already fsync'd at
        append). The replacement is a fresh Operator on the same durable
        substrate: it stands by until the dead incumbent's lease goes stale
        (~15s virtual time), takes over, and runs Operator.recover()
        against the re-read journal before its first resync — adoption by
        idempotency key, orphan marking + GC expedite, and disruption
        rollback all happen there. In-process solver state dies with the
        operator (same cold-engine discipline as _restart_solverd), so the
        warm-restart contract — zero steady recompiles when the AOT cache
        is configured — is honestly exercised by the re-prewarm."""
        from karpenter_tpu.aot import runtime as aotrt
        from karpenter_tpu.controllers.provisioning import (
            provisioner as provmod,
        )
        from karpenter_tpu.observability import kernels as kobs

        self._recovery["crashes"] += 1
        self.log.append(
            self._rel(self.clock.now()),
            "operator-crash",
            barrier=crash.barrier,
            action=crash.action or "",
        )
        old = self.operator
        old.journal.close()
        try:
            old.provisioner.solver.close()
        except Exception:  # noqa: BLE001 — a dying process can't block the sim
            pass
        # flight/SLO sources re-register under the same keys (keyed
        # replace), so the dead operator's callbacks fall away with it
        self.operator = Operator(
            self.store, self.provider, clock=self.clock, options=self.options
        )
        if self._solver_factory is not None:
            self.operator.provisioner.solver = self._solver_factory(self)
        self.operator.tracer = self.tracer
        self.operator.on_recover = self._on_recover
        # the new process's breaker transitions belong in the same
        # observable record as the old one's
        self.operator.breaker.subscribe(
            lambda old_state, new_state: self.log.append(
                self._rel(self.clock.now()),
                "breaker",
                **{"from": old_state, "to": new_state},
            )
        )
        # the scenario's fault profile survives the restart: re-wrap the
        # fresh client, continuing the established rng stream (byte
        # determinism depends on continuing it, not reseeding)
        if self._solver_rejection_rate > 0:
            self.operator.provisioner.solver = FlakySolverClient(
                self.operator.provisioner.solver,
                rng=self._solver_fault_rng,
                rejection_rate=self._solver_rejection_rate,
                on_fault=self._on_fault,
            )
        # cold-engine discipline, exactly as _restart_solverd: the crashed
        # process's engines and executables are gone; a configured AOT
        # cache is what makes the re-prewarm fast instead of a recompile
        provmod._ENGINE_CONTENT_CACHE.clear()
        aotrt.clear_executables()
        if aotrt.enabled():
            try:
                import jax

                jax.clear_caches()
            except Exception:  # noqa: BLE001 — jax never imported: nothing to clear
                pass
        kobs.registry().unseal()

    def _submit(self, group: _Group, name: str) -> None:
        pod = build_pod(name, group.name, group.pod_spec)
        self.store.create(pod)
        self.log.append(
            self._rel(self.clock.now()), "pod-submitted", pod=name, group=group.name
        )

    # -- workload driver (ReplicaSet + pod-GC stand-ins) ---------------------

    def _workloads(self) -> None:
        now = self.clock.now()
        node_names = {n.metadata.name for n in self.store.list("Node")}
        for group in self._groups.values():
            if group.done:
                continue
            live = self.store.list(
                "Pod",
                predicate=lambda p: p.metadata.labels.get(GROUP_LABEL) == group.name,
            )
            if group.until is not None and now >= group.until:
                for p in live:
                    self.store.delete(p)
                group.done = True
                self.log.append(
                    self._rel(now), "group-complete", group=group.name, pods=len(live)
                )
                continue
            # pod-GC: a pod bound to a node that vanished out-of-band is lost
            survivors = []
            for p in live:
                if p.spec.node_name and p.spec.node_name not in node_names:
                    self.store.delete(p)
                    self.log.append(
                        self._rel(now), "pod-lost", pod=p.metadata.name,
                        node=p.spec.node_name,
                    )
                else:
                    survivors.append(p)
            # ReplicaSet stand-in: top the group back up to desired
            if group.replace:
                for _ in range(group.desired - len(survivors)):
                    group.serial += 1
                    self._submit(group, f"{group.name}-r{group.serial}")

    # -- state observation ---------------------------------------------------

    def _observe(self, summary: dict) -> None:
        t = self._rel(self.clock.now())
        nodes = {n.metadata.name: n for n in self.store.list("Node")}
        for name in sorted(nodes.keys() - self._known_nodes):
            facts = node_facts(nodes[name])
            self.log.append(t, "node-added", node=name, **facts)
        for name in sorted(self._known_nodes - nodes.keys()):
            self.log.append(t, "node-deleted", node=name)
        self._known_nodes = set(nodes)
        claims = {c.metadata.name for c in self.store.list("NodeClaim")}
        for name in sorted(claims - self._known_claims):
            self.log.append(t, "nodeclaim-added", nodeclaim=name)
        for name in sorted(self._known_claims - claims):
            self.log.append(t, "nodeclaim-deleted", nodeclaim=name)
        self._known_claims = claims
        bound_now = set()
        for p in self.store.list("Pod", predicate=lambda p: p.spec.node_name != ""):
            bound_now.add(p.metadata.name)
            if p.metadata.name not in self._bound:
                self.log.append(
                    t, "pod-bound", pod=p.metadata.name, node=p.spec.node_name
                )
        self._bound = bound_now
        if any(summary.values()):
            self.log.append(t, "pass", **summary)


def run_scenario(
    trace: dict,
    seed: int,
    options: Optional[Options] = None,
    trace_export: Optional[str] = None,
) -> SimResult:
    return Simulation(trace, seed, options=options, trace_export=trace_export).run()
