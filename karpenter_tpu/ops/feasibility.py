"""The feasibility kernel: batched requirement-intersection on device.

This is the TPU replacement for the reference's hottest loop,
`filterInstanceTypesByRequirements` (pkg/controllers/provisioning/scheduling/
nodeclaim.go:373-441), factorized as:

    ReqCompat[R, I]  — every distinct Requirement row vs every instance type
    compat[P, I]     — AND over each pod's rows, via membership matmul
    fits[P, I]       — resource vector comparison
    offering[P, I]   — any available offering compatible per instance

Set-intersection semantics mirror pkg/scheduling/requirement.go:194-228
(HasIntersection) and requirements.go:248-268 (Intersects: only shared keys
constrain; NotIn/DoesNotExist pairs are exempt).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.ops.encoding import NO_GT, NO_LT, NOT_INT, WORD


def unpack_mask(words: jnp.ndarray) -> jnp.ndarray:
    """[..., W] uint32 → [..., W*32] bool."""
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = (words[..., None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*words.shape[:-1], words.shape[-1] * WORD).astype(bool)


def _bounds_ok(gt: jnp.ndarray, lt: jnp.ndarray, value_int: jnp.ndarray) -> jnp.ndarray:
    """Per-slot integer-bounds admissibility.

    gt/lt: [...] broadcastable against value_int [G]. When neither bound is
    set every slot passes; otherwise non-integer slots fail
    (requirement.go:308-324).
    """
    unbounded = (gt == NO_GT) & (lt == NO_LT)
    is_int = value_int != NOT_INT
    in_range = is_int & (value_int > gt) & (value_int < lt)
    return unbounded | in_range


@functools.partial(jax.jit, static_argnames=())
def req_rows_vs_sets(
    # requirement rows [R]
    row_key: jnp.ndarray,  # [R] int32
    row_complement: jnp.ndarray,  # [R] bool
    row_has_values: jnp.ndarray,  # [R] bool
    row_gt: jnp.ndarray,  # [R] int32
    row_lt: jnp.ndarray,  # [R] int32
    row_mask: jnp.ndarray,  # [R, W] uint32
    # requirement sets [N]
    set_present: jnp.ndarray,  # [N, K] bool
    set_complement: jnp.ndarray,  # [N, K] bool
    set_has_values: jnp.ndarray,  # [N, K] bool
    set_gt: jnp.ndarray,  # [N, K] int32
    set_lt: jnp.ndarray,  # [N, K] int32
    set_mask: jnp.ndarray,  # [N, W] uint32
    # vocab tables
    slot_key: jnp.ndarray,  # [G] int32
    value_int: jnp.ndarray,  # [G] int32
) -> jnp.ndarray:
    """compat[R, N]: does requirement row r intersect set n on r's key?

    Mirrors Intersects() semantics: a key the set doesn't constrain is
    compatible; NotIn/DoesNotExist on both sides is exempt from the
    intersection test.
    """
    R = row_key.shape[0]
    N = set_present.shape[0]

    # Gather the set's per-key metadata at each row's key: [R, N]
    present = set_present[:, row_key].T  # [N, K][:, R] -> [N, R] -> T
    s_comp = set_complement[:, row_key].T
    s_hasv = set_has_values[:, row_key].T
    s_gt = set_gt[:, row_key].T
    s_lt = set_lt[:, row_key].T

    g = jnp.maximum(row_gt[:, None], s_gt)  # [R, N]
    l = jnp.minimum(row_lt[:, None], s_lt)
    bounds_empty = (g != NO_GT) & (l != NO_LT) & (g >= l)

    both_complement = row_complement[:, None] & s_comp  # [R, N]

    # Candidate slots: restrict to the row's key, honor complements & bounds.
    row_bits = unpack_mask(row_mask)  # [R, G]
    set_bits = unpack_mask(set_mask)  # [N, G]
    key_slots = slot_key[None, :] == row_key[:, None]  # [R, G]
    a_bits = jnp.where(row_complement[:, None], ~row_bits, row_bits) & key_slots
    # set side: complement per (row,key); expand to [R, N, G]
    b_raw = set_bits[None, :, :]  # [1, N, G]
    b_bits = jnp.where(s_comp[:, :, None], ~b_raw, b_raw)  # [R, N, G]
    bounds = _bounds_ok(g[:, :, None], l[:, :, None], value_int[None, None, :])
    candidates = a_bits[:, None, :] & b_bits & bounds  # [R, N, G]
    any_candidate = jnp.any(candidates, axis=-1)  # [R, N]

    has_intersection = jnp.where(
        bounds_empty, False, jnp.where(both_complement, True, any_candidate)
    )

    # NotIn/DoesNotExist exemption (requirements.go:253-259)
    row_exempt = (row_complement & row_has_values) | (~row_complement & ~row_has_values)
    set_exempt = (s_comp & s_hasv) | (~s_comp & ~s_hasv)
    exempt = row_exempt[:, None] & set_exempt

    return ~present | has_intersection | exempt


def req_rows_vs_sets_np(
    row_key: np.ndarray,
    row_complement: np.ndarray,
    row_has_values: np.ndarray,
    row_gt: np.ndarray,
    row_lt: np.ndarray,
    row_mask: np.ndarray,
    set_present: np.ndarray,
    set_complement: np.ndarray,
    set_has_values: np.ndarray,
    set_gt: np.ndarray,
    set_lt: np.ndarray,
    set_mask: np.ndarray,
    slot_key: np.ndarray,
    value_int: np.ndarray,
) -> np.ndarray:
    """Host twin of req_rows_vs_sets: identical integer/bool semantics in
    numpy, for incremental row batches too small to pay device dispatch
    (the sequential FFD simulation interns joint-requirement rows one claim
    at a time)."""

    def unpack(words: np.ndarray) -> np.ndarray:
        shifts = np.arange(WORD, dtype=np.uint32)
        bits = (words[..., None] >> shifts) & np.uint32(1)
        return bits.reshape(*words.shape[:-1], words.shape[-1] * WORD).astype(bool)

    present = set_present[:, row_key].T
    s_comp = set_complement[:, row_key].T
    s_hasv = set_has_values[:, row_key].T
    s_gt = set_gt[:, row_key].T
    s_lt = set_lt[:, row_key].T

    g = np.maximum(row_gt[:, None], s_gt)
    l = np.minimum(row_lt[:, None], s_lt)
    bounds_empty = (g != NO_GT) & (l != NO_LT) & (g >= l)
    both_complement = row_complement[:, None] & s_comp

    row_bits = unpack(row_mask)
    set_bits = unpack(set_mask)
    key_slots = slot_key[None, :] == row_key[:, None]
    a_bits = np.where(row_complement[:, None], ~row_bits, row_bits) & key_slots
    b_raw = set_bits[None, :, :]
    b_bits = np.where(s_comp[:, :, None], ~b_raw, b_raw)
    unbounded = (g == NO_GT) & (l == NO_LT)
    is_int = value_int != NOT_INT
    in_range = (
        is_int[None, None, :]
        & (value_int[None, None, :] > g[:, :, None])
        & (value_int[None, None, :] < l[:, :, None])
    )
    bounds = unbounded[:, :, None] | in_range
    candidates = a_bits[:, None, :] & b_bits & bounds
    any_candidate = np.any(candidates, axis=-1)

    has_intersection = np.where(
        bounds_empty, False, np.where(both_complement, True, any_candidate)
    )
    row_exempt = (row_complement & row_has_values) | (~row_complement & ~row_has_values)
    set_exempt = (s_comp & s_hasv) | (~s_comp & ~s_hasv)
    exempt = row_exempt[:, None] & set_exempt
    return ~present | has_intersection | exempt


@jax.jit
def membership_all(membership: jnp.ndarray, row_ok: jnp.ndarray) -> jnp.ndarray:
    """all-rows-compatible via matmul.

    membership: [P, R] bool — entity p constrained by requirement row r
    row_ok:     [R, N] bool — row r compatible with target n
    returns     [P, N] bool — every row of p compatible with n

    The float matmul counts incompatible rows per (p, n) — this is the
    MXU-shaped core of the sweep.
    """
    bad = membership.astype(jnp.float32) @ (~row_ok).astype(jnp.float32)
    return bad < 0.5


def membership_all_np(membership: np.ndarray, row_ok: np.ndarray) -> np.ndarray:
    """Host twin of membership_all (float32 BLAS; counts are small integers
    represented exactly, so the <0.5 threshold is bit-identical)."""
    bad = membership.astype(np.float32) @ (~row_ok).astype(np.float32)
    return bad < 0.5


def offering_reduce_np(
    membership: np.ndarray,
    offer_compat: np.ndarray,
    custom_need: np.ndarray,
    key_present: np.ndarray,
    available: np.ndarray,
    offering_owner: np.ndarray,
    num_instances: int,
) -> np.ndarray:
    """Host twin of offering_reduce. The offering→instance any-reduce uses a
    per-row scatter instead of the [O, I] one-hot matmul — the host path only
    runs for cubes small enough that the matmul would be waste."""
    offer_rows_ok = membership_all_np(membership, offer_compat)  # [P, O]
    bad = custom_need.astype(np.float32) @ (~key_present).astype(np.float32).T
    undef_ok = (bad < 0.5).T  # [P, O]
    offer_ok = offer_rows_ok & undef_ok & available[None, :]
    P = membership.shape[0]
    out = np.zeros((P, num_instances), dtype=bool)
    for p in range(P):
        out[p, offering_owner[offer_ok[p]]] = True
    return out


@jax.jit
def fits_matrix(requests: jnp.ndarray, allocatable: jnp.ndarray) -> jnp.ndarray:
    """fits[P, I]: requests[p] <= allocatable[i] element-wise.

    requests:    [P, D] (missing resources must be 0)
    allocatable: [I, D] (resources the node lacks must be 0)
    Mirrors resources.Fits: a positive request against a zero capacity fails.
    Callers on the exact-parity path must pass integer-quantized units (see
    quantize_resources) — float32 alone loses ~512B at 8GiB scale.
    """
    return jnp.all(requests[:, None, :] <= allocatable[None, :, :], axis=-1)


_BYTE_SCALE_PREFIXES = ("memory", "ephemeral-storage", "hugepages-")


def resource_scales(dims: dict[str, int]) -> np.ndarray:
    """Per-dimension quantization multipliers keeping values in int32 range:
    byte-denominated resources quantize to MiB, everything else to
    milli-units (cpu "100m" stays exact; 2 PiB memory still fits int32)."""
    scales = np.full(len(dims), 1000.0)
    for name, i in dims.items():
        if name.startswith(_BYTE_SCALE_PREFIXES):
            scales[i] = 1.0 / float(2**20)
    return scales


def quantize_resources(
    values: np.ndarray, ceil: bool, scales: np.ndarray | float = 1000.0
) -> np.ndarray:
    """float64 [., D] resources → int32-safe integer units, rounded
    conservatively: requests round up, capacities round down, so the integer
    comparison can only be stricter than the float64 host oracle, never
    looser. Saturation is asymmetric for the same reason — an oversized
    request clips ABOVE any clipped capacity, so it can never falsely fit."""
    scaled = values * scales
    if ceil:
        out = np.ceil(scaled - 1e-6)
        return np.clip(out, -(2**31) + 1, 2**31 - 1).astype(np.int64)
    out = np.floor(scaled + 1e-6)
    return np.clip(out, -(2**31) + 1, 2**30).astype(np.int64)


def _cube_math(
    membership,
    req_compat,
    offer_compat,
    custom_need,
    key_present,
    available,
    owner_onehot,
):
    """compat[P, I] and has_offering[P, I] in one program — the production
    feasibility cube (both membership matmuls + offering reduce fused)."""
    bad = membership.astype(jnp.float32) @ (~req_compat).astype(jnp.float32)
    compat = bad < 0.5
    offer_bad = membership.astype(jnp.float32) @ (~offer_compat).astype(jnp.float32)
    offer_rows_ok = offer_bad < 0.5
    undef_bad = custom_need.astype(jnp.float32) @ (~key_present).astype(jnp.float32).T
    undef_ok = (undef_bad < 0.5).T
    offer_ok = offer_rows_ok & undef_ok & available[None, :]
    has_offering = (
        offer_ok.astype(jnp.float32) @ owner_onehot.astype(jnp.float32)
    ) > 0.5
    return compat, has_offering


production_cube = jax.jit(_cube_math)

_sharded_cube_cache: dict = {}


def mesh_scope(mesh) -> str:
    """The AOT table/cache scope of a mesh: device count + axis names.
    Sharded dispatches pad to mesh-size-INVARIANT global shapes
    (aot/ladder.MESH_ALIGN), so the device layout must be carried by this
    scope — in the runtime executable table and the persistent cache key,
    never in the observatory shape signature (kernel digests stay
    mesh-invariant by construction)."""
    n = int(np.prod(mesh.devices.shape))
    return f"mesh={n}:{','.join(mesh.axis_names)}"


def sharded_cube(mesh):
    """The production cube under shard_map: the entity axis (pods/groups ×
    templates) is data-parallel across the mesh, the catalog matrices are
    replicated, so every matmul is local to its chip and no collectives are
    needed until results gather (SURVEY §7: DP-style sharding of the pod
    dimension over ICI)."""
    fn = _sharded_cube_cache.get(mesh)
    if fn is None:
        try:
            from jax import shard_map
        except ImportError:  # jax < 0.6 keeps shard_map under jax.experimental
            from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        axis = mesh.axis_names[0]
        fn = jax.jit(
            shard_map(
                _cube_math,
                mesh=mesh,
                in_specs=(P(axis), P(), P(), P(), P(axis), P(), P()),
                out_specs=(P(axis), P(axis)),
            )
        )
        _sharded_cube_cache[mesh] = fn
    return fn


def uid_project(uid_onehot, type_mask):
    """surviving-unique-alloc projection: does ANY instance type in
    `type_mask` map onto unique-allocatable row u? The float matmul counts
    surviving types per row (small integers, exact) — the same MXU-shaped
    trick as membership_all. Used inside the fused FFD scan
    (packer._solve_scan_core) for claim-narrowing keep masks and
    limits-narrowed opens; traceable (jnp) and host (np) alike.

    uid_onehot: [U, I] bool — uid_of_type scattered one-hot
    type_mask:  [..., I] bool
    returns     [..., U] bool
    """
    if isinstance(type_mask, np.ndarray):
        return (
            type_mask.astype(np.float32) @ uid_onehot.astype(np.float32).T
        ) > 0.5
    return (
        type_mask.astype(jnp.float32) @ uid_onehot.astype(jnp.float32).T
    ) > 0.5


def uid_onehot_matrix(uid_of_type: np.ndarray, num_uniq: int) -> np.ndarray:
    """[U, I] bool one-hot of uid_of_type — the projection operand
    uid_project consumes (built once per engine catalog)."""
    I = uid_of_type.shape[0]
    out = np.zeros((num_uniq, I), dtype=bool)
    out[uid_of_type, np.arange(I)] = True
    return out


# -- decision provenance (observability/explain.py) --------------------------
#
# The cube computes compat/fits/has_offering [P, I] before AND-ing them
# into `feasible`; the stage plane keeps the provenance: one uint8 code per
# (pod, instance-type) naming the FIRST stage that eliminated the pair, in
# funnel order (requirements -> resources -> offerings; 0 = survived). The
# math is elementwise over planes the sweep already materialized — no new
# laddered kernel shapes, so capture cannot perturb the zero-recompile
# seal. The serving path decodes host-side (`stage_plane_np` over the
# fetched bool planes); the jit twin exists for device-resident pipelines.

STAGE_OK = 0
STAGE_REQUIREMENTS = 1
STAGE_RESOURCES = 2
STAGE_OFFERINGS = 3
STAGE_NAMES = {
    STAGE_REQUIREMENTS: "requirements",
    STAGE_RESOURCES: "resources",
    STAGE_OFFERINGS: "offerings",
}


@jax.jit
def stage_plane(
    compat: jnp.ndarray, fits: jnp.ndarray, has_offering: jnp.ndarray
) -> jnp.ndarray:
    """[..., I] uint8 first-failing-stage codes from the cube's planes."""
    return jnp.where(
        ~compat,
        jnp.uint8(STAGE_REQUIREMENTS),
        jnp.where(
            ~fits,
            jnp.uint8(STAGE_RESOURCES),
            jnp.where(
                ~has_offering, jnp.uint8(STAGE_OFFERINGS), jnp.uint8(STAGE_OK)
            ),
        ),
    )


def stage_plane_np(
    compat: np.ndarray, fits: np.ndarray, has_offering: np.ndarray
) -> np.ndarray:
    """Host twin of stage_plane (identical codes, numpy)."""
    return np.where(
        ~compat,
        np.uint8(STAGE_REQUIREMENTS),
        np.where(
            ~fits,
            np.uint8(STAGE_RESOURCES),
            np.where(
                ~has_offering, np.uint8(STAGE_OFFERINGS), np.uint8(STAGE_OK)
            ),
        ),
    ).astype(np.uint8)


def stage_counts(plane: np.ndarray) -> dict[str, int]:
    """Decode a stage plane into per-stage elimination counts (survivors
    excluded) — the interned-vocabulary form the explain ledger records."""
    counts = np.bincount(np.asarray(plane, dtype=np.uint8).ravel(), minlength=4)
    return {
        name: int(counts[code])
        for code, name in STAGE_NAMES.items()
        if counts[code]
    }


@jax.jit
def offering_reduce(
    membership: jnp.ndarray,  # [P, R] bool
    offer_compat: jnp.ndarray,  # [R, O] bool — row r compatible with offering o
    custom_need: jnp.ndarray,  # [O, K] bool — offering needs custom key k defined
    key_present: jnp.ndarray,  # [P, K] bool — query set defines key k
    available: jnp.ndarray,  # [O] bool
    owner_onehot: jnp.ndarray,  # [O, I] bool
) -> jnp.ndarray:
    """has_offering[P, I]: any available, fully-compatible offering per type.

    Fuses the three offering gates (row compat, undefined-custom-label rule,
    availability) and the offering→instance any-reduce into one device
    program (scheduling/nodeclaim.go:414-433 semantics).
    """
    offer_rows_ok = membership_all(membership, offer_compat)  # [P, O]
    bad = custom_need.astype(jnp.float32) @ (~key_present).astype(jnp.float32).T
    undef_ok = (bad < 0.5).T  # [P, O]
    offer_ok = offer_rows_ok & undef_ok & available[None, :]
    return (offer_ok.astype(jnp.float32) @ owner_onehot.astype(jnp.float32)) > 0.5
