"""The one-dispatch solve: host builders + decode around packer._solve_scan.

A steady-state solve used to be a host-paced conversation — device sweeps
(feasibility, packing) interleaved with host heap scans, claim-opening
memos, and per-round frontier RTTs. This driver reformulates the monotone
FFD scan itself as ONE device-resident `lax.while_loop` dispatch
(ops/packer.solve_scan_fn): the host side precomputes the *monotone
verdict tables* the scan branches on — requirement-family transition
closures, claim-opening candidates, existing-node compatibility, nodepool
limit budgets — all of it from engine caches that stay warm across passes,
then dispatches once and decodes the placement back into the standard
`_DeviceSolve` claim/node structures, whose inherited `emit()` finishes the
solve exactly like the host walk.

The host walk (ffd._DeviceSolve.run / the native kernel) remains both the
semantics oracle — the `fused` parity fuzz modes assert bit-for-bit
decision identity, error strings included — and the slow-path fallback:
shapes the scan doesn't cover decline with a metered taxonomy reason
(`karpenter_scheduler_fused_declines_total{reason=}`):

    topo           topology/preferences/strict-reserved routed solves
    min            minValues templates (host diversity gates)
    reserved       reserved-capacity bookkeeping (host can_add cycle)
    templates      no/too many nodeclaim templates
    size           pod/group/node/fam axes past the scan buckets
    nodes          existing-node requirement state that later joins could
                   narrow (non-single-valued rows on a group-constrained
                   key) — static node compatibility would be unsound
    claim-overflow / queue-overflow
                   post-dispatch aborts (the scan ran out of claim slots
                   or requeue capacity; the host walk re-solves)
    divergence     the decode's host-side error recomputation disagreed
                   with the device placement (guard rail; STRICT raises)

Eligibility is decided per batch; a decline costs the host walk it would
have run anyway. Float comparisons run in real float64 on device
(packer.scan_x64) with subtractions in the host's exact per-join order, so
decisions — including epsilon-threshold fit edges — are bit-identical.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.metrics import global_registry
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops import packer
from karpenter_tpu.scheduling.taints import Taints
from karpenter_tpu.tracing import kernel as ktime
from karpenter_tpu.utils import resources as res

# -- mode + metering ----------------------------------------------------------

# off: never fuse. on: fuse every eligible batch. auto (default): fuse only
# on non-CPU backends — on CPU the native C kernel out-runs an XLA
# while_loop, and keeping auto off-CPU leaves every existing sim digest and
# bench leg byte-stable. Tests, the fused bench leg, and the fused-smoke CI
# job opt in explicitly (KARPENTER_TPU_FUSED=on / --fused-solve on).
FUSED_MODE = os.environ.get("KARPENTER_TPU_FUSED", "auto").strip().lower() or "auto"

FUSED_SOLVES = 0
FUSED_DECLINES: dict[str, int] = {}
_FUSED_SOLVES_CTR = global_registry.counter(
    "karpenter_scheduler_fused_solves_total",
    "scheduling solves executed as one fused device dispatch",
)
_FUSED_DECLINES_CTR = global_registry.counter(
    "karpenter_scheduler_fused_declines_total",
    "fused-solve declines back to the host walk, by taxonomy reason",
    labels=["reason"],
)

# scan bucket caps: past these the fused executable universe stops being
# worth pinning — the host walk is the designed slow path
FUSED_MAX_PODS = 1 << 17
FUSED_MAX_GROUPS = 4096
FUSED_MAX_NODES = 4096
FUSED_MAX_FAMS = 1024
FUSED_MAX_TEMPLATES = 8
# with limits active the per-step transition evaluation carries full
# instance-axis masks (exact, but heavier) — cap the batch size it runs at
FUSED_LIMITS_MAX_PODS = 8192


def note_decline(reason: str) -> None:
    FUSED_DECLINES[reason] = FUSED_DECLINES.get(reason, 0) + 1
    _FUSED_DECLINES_CTR.inc({"reason": reason})
    # fold the decline taxonomy into the provenance ledger (`fused:<reason>`
    # stages): a decline reroutes the batch to the host walk, whose per-pod
    # errors stage normally, so per-pod explanations stay path-identical
    from karpenter_tpu.observability import explain as explmod

    explmod.recorder().note_fused_decline(reason)


def fused_counters() -> dict:
    out = {"fused_solves": FUSED_SOLVES}
    for reason, n in sorted(FUSED_DECLINES.items()):
        out[f"fused_decline_{reason}"] = n
    return out


def fused_enabled() -> bool:
    mode = FUSED_MODE
    if mode in ("on", "1", "true"):
        return True
    if mode in ("off", "0", "false", ""):
        return False
    # auto: the scan wins where dispatch round-trips dominate (real
    # accelerators); on CPU the native kernel stays the fast path
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 — no backend, no fusing
        return False


class _FusedDecline(ffd._Fallback):
    """Internal: this batch isn't scan-shaped — run the host walk."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason
        note_decline(reason)


def _pow2(n: int, floor: int) -> int:
    return max(floor, 1 << max(0, (int(n) - 1).bit_length()))


class _FusedSolve(ffd._DeviceSolve):
    """One-dispatch variant of the device solve: same encode, same emit,
    the queue walk replaced by the device-resident scan."""

    def run(self, timeout: Optional[float]) -> None:
        gi_arr = self._group_pods()
        if gi_arr is None:
            raise ffd._IneligibleShape("ineligible pod shape")
        if self.res_active:
            raise _FusedDecline("reserved")
        T = len(self.s.nodeclaim_templates)
        if not (0 < T <= FUSED_MAX_TEMPLATES):
            raise _FusedDecline("templates")
        self._prepare_templates()
        if self.min_active:
            raise _FusedDecline("min")
        order = self._order(gi_arr)
        self._fused_solve(gi_arr, order)
        self.timed_out = False

    # -- builders ------------------------------------------------------------

    def _group_reps(self, gi_arr: np.ndarray, order: np.ndarray) -> list:
        """One representative pod per group (tolerations/taints are part of
        the shape signature, so any member answers for the group)."""
        reps: list = [None] * len(self.groups)
        remaining = len(self.groups)
        for i in order:
            gi = int(gi_arr[i])
            if reps[gi] is None:
                reps[gi] = self.pods[int(i)]
                remaining -= 1
                if not remaining:
                    break
        return reps

    def _node_tensors(self, reps: list):
        """Static per-(node, group) admissibility + headroom vectors. Sound
        only when no group join can change a node's requirement VALUES —
        every group-constrained key must already be a single-valued In row
        on the node, making the host's joint-narrowing a value-no-op."""
        ens = self.s.existing_nodes
        N = len(ens)
        if N == 0:
            return None, None
        if N > FUSED_MAX_NODES:
            raise _FusedDecline("size")
        group_keys = sorted({r.key for g in self.groups for r in g.reqs})
        G = len(self.groups)
        node_ok = np.zeros((N, G), dtype=bool)
        node_rem = np.zeros((N, self.D), dtype=np.float64)
        for j, en in enumerate(ens):
            reqs = en.requirements
            for key in group_keys:
                if not reqs.has(key):
                    raise _FusedDecline("nodes")
                r = reqs.get(key)
                if (
                    r.complement
                    or r.greater_than is not None
                    or r.less_than is not None
                    or len(r.values) != 1
                ):
                    raise _FusedDecline("nodes")
            taints = Taints(en.cached_taints)
            for gi, g in enumerate(self.groups):
                node_ok[j, gi] = (
                    taints.tolerates_pod(reps[gi]) is None
                    and reqs.compatible(g.reqs) is None
                )
            for name, v in en.remaining_resources.items():
                d = self.dims.get(name)
                if d is not None:
                    node_rem[j, d] = v
        return node_ok, node_rem

    def _closure(self):
        """Transitive closure of the requirement-family transition graph
        from every opening family over every group — the scan's verdict
        tables. All requirement algebra rides the engine-level caches
        (solver_fam_trans, solver_joint_cache), so steady-state passes
        rebuild this from warm dictionaries without a single sweep."""
        G = len(self.groups)
        kinds: list[np.ndarray] = []
        fams: list[np.ndarray] = []
        done = 0
        while done < len(self.fam_rows):
            if len(self.fam_rows) > FUSED_MAX_FAMS:
                raise _FusedDecline("closure")
            f = done
            done += 1
            krow = np.zeros(G, dtype=np.int8)
            frow = np.zeros(G, dtype=np.int32)
            for gi in range(G):
                ent = self.fam_join.get((f, gi))
                if ent is None:
                    ent = self._build_fam_join(f, gi)
                kind = ent[0]
                if kind == self._REJECT:
                    krow[gi] = packer._KIND_REJECT
                elif kind == self._SAME:
                    krow[gi] = packer._KIND_SAME
                    frow[gi] = f
                else:
                    krow[gi] = packer._KIND_NARROW
                    frow[gi] = ent[1]
            kinds.append(krow)
            fams.append(frow)
        F = len(self.fam_rows)
        trans_kind = np.stack(kinds) if kinds else np.zeros((0, G), np.int8)
        trans_fam = np.stack(fams) if fams else np.zeros((0, G), np.int32)
        fam_mask = np.zeros((F, self.I), dtype=bool)
        for f in range(F):
            compat_v, offer_v = self._joint_masks(
                self.fam_rows[f], self.fam_reqs[f]
            )
            fam_mask[f] = compat_v & offer_v
        return trans_kind, trans_fam, fam_mask

    def _open_tensors(self):
        """Per-(template, group) opening verdicts from the memoized
        limitless open entries (the exact tables _new_claim consults)."""
        T = len(self.s.nodeclaim_templates)
        G = len(self.groups)
        open_ok = np.zeros((T, G), dtype=bool)
        open_fam = np.zeros((T, G), dtype=np.int32)
        open_uok = np.zeros((T, G, self.U), dtype=bool)
        open_cand = np.zeros((T, G, self.I), dtype=bool)
        tol = np.zeros((T, G), dtype=bool)
        for ti in range(T):
            for gi in range(G):
                if self._tg(ti, gi) is None:
                    continue
                entry = self._ensure_open_entry(ti, gi)
                if entry[0] < 0:
                    continue
                fam, candidate0, u_ids0, _rem, _specs, _relaxed = entry
                open_ok[ti, gi] = True
                open_fam[ti, gi] = fam
                open_uok[ti, gi, u_ids0] = True
                open_cand[ti, gi] = candidate0
        return open_ok, open_fam, open_uok, open_cand, tol

    def _fill_tol(self, tol: np.ndarray, reps: list) -> None:
        for ti, nct in enumerate(self.s.nodeclaim_templates):
            taints = Taints(nct.spec.taints)
            for gi in range(len(self.groups)):
                got = self.tg_tol.get((ti, gi))
                if got is None:
                    got = taints.tolerates_pod(reps[gi]) is None
                    self.tg_tol[(ti, gi)] = got
                tol[ti, gi] = got

    def _limit_tensors(self):
        """Nodepool limit budgets as dense dim vectors + presence masks.
        Non-dim limit entries never move (subtract_max only touches dims):
        a negative one permanently empties the pool's mask (pool_bad)."""
        _EPS = ffd._EPS
        pools: list[str] = []
        pool_idx: dict[str, int] = {}
        T = len(self.s.nodeclaim_templates)
        pool_of_t = np.full(T, -1, dtype=np.int32)
        for ti, nct in enumerate(self.s.nodeclaim_templates):
            remaining = self.remaining_resources.get(nct.nodepool_name)
            if not remaining:
                continue
            li = pool_idx.get(nct.nodepool_name)
            if li is None:
                li = pool_idx[nct.nodepool_name] = len(pools)
                pools.append(nct.nodepool_name)
            pool_of_t[ti] = li
        L = len(pools)
        if L == 0:
            return None
        pool_rem = np.zeros((L, self.D), dtype=np.float64)
        pool_has = np.zeros((L, self.D), dtype=bool)
        pool_bad = np.zeros(L, dtype=bool)
        for li, name in enumerate(pools):
            for key, limit in self.remaining_resources[name].items():
                d = self.dims.get(key)
                if d is None:
                    if 0.0 > limit + _EPS:
                        pool_bad[li] = True
                else:
                    pool_rem[li, d] = limit
                    pool_has[li, d] = True
        return pools, pool_of_t, pool_rem, pool_has, pool_bad

    def _claim_estimate(self, open_ok, open_fam, gi_arr) -> int:
        """Rough upper estimate of how many claims this batch opens: per
        group, pods over the best single-group claim capacity. Not a proof
        (mixed-group packing can open more) — the scan aborts with
        SCAN_CLAIM_OVERFLOW past the bucket and the host walk re-solves, so
        a low estimate costs a metered decline, never a wrong answer."""
        counts = np.bincount(gi_arr, minlength=len(self.groups))
        est = 1
        for gi, g in enumerate(self.groups):
            n = int(counts[gi])
            if n == 0:
                continue
            best = 1
            for ti in range(open_ok.shape[0]):
                if not open_ok[ti, gi]:
                    continue
                entry = self.open_cache.get((ti, gi))
                if entry is None or entry[0] < 0:
                    continue
                rem0 = entry[3]
                per_dim = np.full_like(rem0, np.inf)
                pos = g.req_f > 0
                if pos.any():
                    per_dim[:, pos] = rem0[:, pos] // g.req_f[pos] + 1
                    best = max(best, int(per_dim.min(axis=1).max()))
                else:
                    best = n
            est += -(-n // max(1, best))
        return est

    # -- dispatch ------------------------------------------------------------

    def _fused_solve(self, gi_arr: np.ndarray, order: np.ndarray) -> None:
        from karpenter_tpu.ops import feasibility as feas

        P_real = len(self.pods)
        G_real = len(self.groups)
        T = len(self.s.nodeclaim_templates)
        if P_real > FUSED_MAX_PODS or G_real > FUSED_MAX_GROUPS:
            raise _FusedDecline("size")
        reps = self._group_reps(gi_arr, order)
        node_ok, node_rem0 = self._node_tensors(reps)
        has_nodes = node_ok is not None
        limits = self._limit_tensors()
        has_limits = limits is not None
        if has_limits and P_real > FUSED_LIMITS_MAX_PODS:
            raise _FusedDecline("size")
        open_ok, open_fam, open_uok, open_cand, tol = self._open_tensors()
        self._fill_tol(tol, reps)
        trans_kind, trans_fam, fam_mask = self._closure()
        F_real = trans_kind.shape[0]
        N_real = len(self.s.existing_nodes) if has_nodes else 0
        L = limits[2].shape[0] if has_limits else 0

        # bucket the variable axes so the executable universe is finite;
        # an attached AOT ladder pins it (warm-startable), else pow2 floors.
        # The claim axis is sized from an estimate, NOT the pod count — the
        # loop-carried claim state (headroom matrices, count tensors) is
        # what every iteration updates in place, so its footprint sets the
        # per-step cost; overflow aborts to the host walk, metered.
        C_est = 2 * self._claim_estimate(open_ok, open_fam, gi_arr) + 64
        ladder = getattr(self.engine, "aot_ladder", None)
        dims = (P_real, G_real, C_est, N_real, F_real, T, L)
        bucket = (
            ladder.bucket_for("packer.solve_scan", dims) if ladder else None
        )
        if bucket is not None:
            Pb, Gb, Cb, Nb, Fb = bucket[:5]
        else:
            if ladder is not None:
                from karpenter_tpu.aot import runtime as aotrt

                aotrt.note_off_ladder(
                    "packer.solve_scan",
                    "x".join(str(_pow2(d, 1)) for d in dims),
                )
            Pb = _pow2(P_real, 512)
            Gb = _pow2(G_real, 32)
            Cb = min(_pow2(C_est, 256), _pow2(P_real, 256))
            Nb = _pow2(N_real, 64) if has_nodes else 0
            Fb = _pow2(F_real, 64)

        D, U, I = self.D, self.U, self.I
        pod_gi = np.full(Pb, -1, dtype=np.int32)
        pod_gi[:P_real] = gi_arr[order]
        g_req = np.zeros((Gb, D), dtype=np.float64)
        g_floor = np.full((Gb, D), -1e-9, dtype=np.float64)
        for gi, g in enumerate(self.groups):
            g_req[gi] = g.req_f
            g_floor[gi] = g.fit_floor

        def padG(a, fill=0):
            out = np.zeros((a.shape[0], Gb) + a.shape[2:], dtype=a.dtype)
            if fill:
                out[:] = fill
            out[:, :G_real] = a
            return out

        tolP = padG(tol)
        open_okP = padG(open_ok)
        open_famP = padG(open_fam)
        open_uokP = padG(open_uok)
        tkP = np.full((Fb, Gb), packer._KIND_REJECT, dtype=np.int8)
        tkP[:F_real, :G_real] = trans_kind
        tfP = np.zeros((Fb, Gb), dtype=np.int32)
        tfP[:F_real, :G_real] = trans_fam
        fam_maskP = np.zeros((Fb, I), dtype=bool)
        fam_maskP[:F_real] = fam_mask
        # uid survival per (template, fam): any instance type in
        # tmpl_mask ∧ fam_mask maps onto the unique-alloc row
        uid_onehot = feas.uid_onehot_matrix(self.uid_of_type, U)
        famu_ok = feas.uid_project(
            uid_onehot, self.tmpl_mask[:, None, :] & fam_maskP[None, :, :]
        )

        dummy2 = np.zeros((1, 1), dtype=np.float64)
        dummyb = np.zeros((1, 1), dtype=bool)
        if has_nodes:
            node_okP = np.zeros((Nb, Gb), dtype=bool)
            node_okP[:N_real, :G_real] = node_ok
            node_remP = np.zeros((Nb, D), dtype=np.float64)
            node_remP[:N_real] = node_rem0
        else:
            node_okP, node_remP = dummyb, dummy2
        if has_limits:
            pools, pool_of_t, pool_rem0, pool_has, pool_bad = limits
            open_candP = padG(open_cand)
            tmpl_maskP = self.tmpl_mask
            cap_fP = self.cap_f.astype(np.float64)
            uid_of_typeP = self.uid_of_type.astype(np.int32)
        else:
            pools, pool_of_t = [], np.full(T, -1, dtype=np.int32)
            pool_rem0, pool_has = dummy2, dummyb
            pool_bad = np.zeros(1, dtype=bool)
            open_candP, tmpl_maskP = dummyb[None], dummyb
            cap_fP = dummy2
            uid_of_typeP = np.zeros(1, dtype=np.int32)

        args = (
            pod_gi, np.zeros(Cb, dtype=np.int32), g_req, g_floor,
            self.uniq_alloc, self.usage0_f,
            tolP, open_okP, open_famP, open_uokP,
            tkP, tfP, famu_ok,
            np.int32(P_real), np.int32(N_real),
            node_okP, node_remP,
            fam_maskP, tmpl_maskP, open_candP,
            uid_onehot, uid_of_typeP, cap_fP,
            pool_of_t, pool_rem0, pool_has, pool_bad,
        )
        mesh = self.engine.mesh
        scope = feas.mesh_scope(mesh) if mesh is not None else ""
        from karpenter_tpu.ops import delta as delta_mod

        if delta_mod.delta_enabled():
            out = self._delta_dispatch(
                args, (T, has_nodes, has_limits), mesh, scope, P_real
            )
        else:
            if mesh is not None:
                fn = packer.sharded_solve_scan(mesh, T, has_nodes, has_limits)
            else:
                fn = packer.solve_scan_fn(T, has_nodes, has_limits)
            with packer.scan_x64():
                out = ktime.dispatch(
                    fn, *args, kernel="packer.solve_scan", aot_scope=scope
                )
        (
            abort, nclaims, pod_claim, pod_node, pod_seq,
            claim_ti, claim_fam, u_valid, tm_st, pool_rem,
        ) = (np.asarray(a) for a in out)
        abort = int(abort)
        if abort == packer.SCAN_CLAIM_OVERFLOW:
            raise _FusedDecline("claim-overflow")
        if abort == packer.SCAN_QUEUE_OVERFLOW:
            raise _FusedDecline("queue-overflow")
        self._decode(
            order, gi_arr, int(nclaims),
            pod_claim[:P_real], pod_node[:P_real], pod_seq[:P_real],
            claim_ti, claim_fam, u_valid, fam_maskP,
            tm_st if has_limits else None,
            (pools, pool_rem) if has_limits else None,
        )
        global_fused_solved()

    # -- delta residency dispatch --------------------------------------------

    def _delta_dispatch(self, args, cfg, mesh, scope, p_real):
        """Residency-aware scan dispatch (ops/delta.py): a cold pass runs
        the full-state scan and commits the 23-component final state as the
        engine's residency; an eligible follow-up pass RESUMES the scan
        against the resident state with every state buffer donated (the
        suffix pods are the only new work). Every N warm passes the warm
        result is also re-solved from scratch and compared bit-for-bit —
        divergence fires a typed event, drops the residency, and the cold
        result wins. Returns the classic 10-output decode subset."""
        from karpenter_tpu.ops import delta as delta_mod

        T, has_nodes, has_limits = cfg
        res = delta_mod.scan_residency(self.engine)
        shape_key = tuple(np.asarray(a).shape for a in args)
        ops_fp = delta_mod.operand_fingerprint(args, skip=(0, 13))
        pod_gi = np.asarray(args[0])
        miss = res.eligibility(cfg, shape_key, ops_fp, pod_gi, p_real)
        if mesh is not None:
            full_fn = packer.sharded_solve_scan_full(mesh, T, has_nodes, has_limits)
            resume_fn = packer.sharded_solve_scan_resume(mesh, T, has_nodes, has_limits)
        else:
            full_fn = packer.solve_scan_full_fn(T, has_nodes, has_limits)
            resume_fn = packer.solve_scan_resume_fn(T, has_nodes, has_limits)
        mode = "cold"
        if miss == "":
            check_due = (
                delta_mod.RESOLVE_FULL_EVERY > 0
                and (res.warm_passes + 1) % delta_mod.RESOLVE_FULL_EVERY == 0
            )
            # the resident buffers are DONATED into this dispatch — clear
            # the residency first so an interrupt can never leave dead
            # buffers installed
            prev_state, prev_lo = res.state, np.int32(res.p_real)
            res.state = None
            delta_mod.note_scan("warm")
            with packer.scan_x64():
                state = ktime.dispatch(
                    resume_fn, *args, *prev_state, prev_lo,
                    kernel="packer.solve_scan_resume", aot_scope=scope,
                )
            res.warm_passes += 1
            res.last_outcome = mode = "warm"
            if check_due:
                with packer.scan_x64():
                    cold = ktime.dispatch(
                        full_fn, *args,
                        kernel="packer.solve_scan_full", aot_scope=scope,
                    )
                identical = all(
                    np.array_equal(np.asarray(state[i]), np.asarray(cold[i]))
                    for i in packer._SCAN_OUT_IDX
                )
                if identical:
                    delta_mod.note_selfcheck("identical")
                    delta_mod.note_pass("warm-check")
                else:
                    delta_mod._emit_divergence(
                        "packer.solve_scan",
                        f"warm resume diverged from the from-scratch "
                        f"re-solve (P={p_real}, warm_pass={res.warm_passes})",
                    )
                    res.invalidate("selfcheck-divergence")
                    state = cold
                    mode = "cold"
        else:
            delta_mod.note_scan(miss)
            res.last_outcome = miss
            with packer.scan_x64():
                state = ktime.dispatch(
                    full_fn, *args,
                    kernel="packer.solve_scan_full", aot_scope=scope,
                )
        delta_mod.note_pass(mode)
        head, tail = int(state[0]), int(state[1])
        stop, abort = bool(np.asarray(state[2])), int(state[3])
        extendable = (
            abort == packer.SCAN_OK
            and not stop
            and head == tail
            and tail == p_real
        )
        res.commit(state, cfg, shape_key, ops_fp, pod_gi, p_real, extendable)
        return packer._scan_finals(state)

    # -- decode --------------------------------------------------------------

    def _decode(
        self, order, gi_arr, nclaims, pod_claim, pod_node, pod_seq,
        claim_ti, claim_fam, u_valid, fam_maskP, tm_st, pool_final,
    ) -> None:
        sorted_pods = [self.pods[int(i)] for i in order]
        gi_sorted = gi_arr[order]
        # claims, in device open order (placeholder hostnames drawn in the
        # same order the host walk would)
        for ci in range(nclaims):
            ti = int(claim_ti[ci])
            fam = int(claim_fam[ci])
            type_mask = self.tmpl_mask[ti] & fam_maskP[fam]
            if tm_st is not None:
                type_mask = type_mask & tm_st[ci]
            c = ffd._Claim(
                ti, fam,
                f"device-placeholder-{next(ffd._placeholder_counter):04d}",
                type_mask,
                np.nonzero(u_valid[ci])[0].astype(np.int64),
                np.zeros((0, self.D)),
                0,
            )
            c.min_specs = self.tmpl_min[ti]
            self.claims.append(c)
        # membership + node joins, in placement order
        placed = np.nonzero(pod_seq >= 0)[0]
        placed = placed[np.argsort(pod_seq[placed], kind="stable")]
        node_joins: dict[int, list[int]] = {}
        for s in placed.tolist():
            pod = sorted_pods[s]
            gi = int(gi_sorted[s])
            ci = int(pod_claim[s])
            if ci >= 0:
                c = self.claims[ci]
                c.count += 1
                c.members.append(pod)
                c.group_counts[gi] = c.group_counts.get(gi, 0) + 1
            else:
                node_joins.setdefault(int(pod_node[s]), []).append(s)
        # node commits: replay the host's per-join dict subtraction so the
        # emitted remaining_resources are bit-identical (incl. non-dim keys)
        for j, joins in node_joins.items():
            nd = self.nodes[j]
            for s in joins:
                pod = sorted_pods[s]
                g = self.groups[int(gi_sorted[s])]
                nd.joined.append(pod)
                nd.remaining = res.subtract(nd.remaining, g.requests)
        # nodepool budgets: device-final dim values, untouched non-dims
        if pool_final is not None:
            pools, pool_rem = pool_final
            for li, name in enumerate(pools):
                remaining = self.remaining_resources[name]
                # float(): keep plain Python floats in the dict (bit-equal
                # values; np scalars would leak into downstream surfaces)
                self.remaining_resources[name] = {
                    k: (float(pool_rem[li, self.dims[k]]) if k in self.dims else v)
                    for k, v in remaining.items()
                }
                # invalidate the limit-mask/open caches the error
                # reconstruction below consults
                self.limits_version += 1
                self.pool_limits_ver[name] = (
                    self.pool_limits_ver.get(name, 0) + 1
                )
        # failures: recompute the host's exact last-attempt errors at final
        # state through the REAL _new_claim. A successful open here means
        # the device and host disagree — guard-rail fallback.
        for s in np.nonzero(pod_seq < 0)[0].tolist():
            pod = sorted_pods[s]
            gi = int(gi_sorted[s])
            if not self.s.nodeclaim_templates:
                self.pod_errors[pod] = ValueError(
                    "nodepool requirements filtered out all available "
                    "instance types"
                )
                continue
            err = self._new_claim(pod, self.groups[gi], gi)
            if err is None:
                raise _FusedDecline("divergence")
            self.pod_errors[pod] = err


def global_fused_solved() -> None:
    global FUSED_SOLVES
    FUSED_SOLVES += 1
    _FUSED_SOLVES_CTR.inc()


def solve_scan_abstract_args(engine, bucket) -> tuple:
    """Abstract (shape, dtype) operands of one fused-scan ladder rung —
    the single source of truth the AOT warm-start walk lowers against.
    MUST mirror _FusedSolve._fused_solve's arg construction exactly, or
    warm-started executables would miss at serve time."""
    import jax

    P, G, C, N, F, T, L = (int(d) for d in bucket)
    has_nodes, has_limits = N > 0, L > 0
    D = len(engine.resource_dims)
    I = engine.num_instances
    U = int(np.unique(engine.allocatable, axis=0).shape[0])
    b, i8, i32, f64 = np.bool_, np.int8, np.int32, np.float64

    def S(shape, dt):
        return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dt))

    return (
        S((P,), i32), S((C,), i32), S((G, D), f64), S((G, D), f64),
        S((U, D), f64), S((T, D), f64),
        S((T, G), b), S((T, G), b), S((T, G), i32), S((T, G, U), b),
        S((F, G), i8), S((F, G), i32), S((T, F, U), b),
        S((), i32), S((), i32),
        S((N, G), b) if has_nodes else S((1, 1), b),
        S((N, D), f64) if has_nodes else S((1, 1), f64),
        S((F, I), b),
        S((T, I), b) if has_limits else S((1, 1), b),
        S((T, G, I), b) if has_limits else S((1, 1, 1), b),
        S((U, I), b),
        S((I,), i32) if has_limits else S((1,), i32),
        S((I, D), f64) if has_limits else S((1, 1), f64),
        S((T,), i32),
        S((L, D), f64) if has_limits else S((1, 1), f64),
        S((L, D), b) if has_limits else S((1, 1), b),
        S((L,), b) if has_limits else S((1,), b),
    )


def solve_scan_state_abstract_args(engine, bucket) -> tuple:
    """Abstract shapes of the 23-component resident scan state for one
    ladder rung — MUST mirror packer._scan_init exactly (under scan_x64,
    so the default-float arrays are f64). The AOT warm-start walk appends
    these (plus the p_lo scalar) to the 27 scan operands to lower the
    donating warm-resume executable (packer.solve_scan_resume_fn)."""
    import jax

    P, G, C, N, F, T, L = (int(d) for d in bucket)
    has_nodes, has_limits = N > 0, L > 0
    D = len(engine.resource_dims)
    I = engine.num_instances if has_limits else 1
    U = int(np.unique(engine.allocatable, axis=0).shape[0])
    b, i32, i64, f64 = np.bool_, np.int32, np.int64, np.float64

    def S(shape, dt):
        return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dt))

    Qcap = 4 * P + 64
    return (
        S((), i32), S((), i32), S((), b), S((), i32),
        S((), i32), S((), i32), S((), i32),
        S((Qcap,), i32),
        S((P,), i32), S((P,), i32), S((P,), i32), S((P,), i32),
        S((C,), i32), S((C,), i32), S((C,), i32), S((C,), i64),
        S((C, U), b), S((C, U, D), f64),
        S((C, G), b), S((G,), i32),
        S((N, D), f64) if has_nodes else S((1, D), f64),
        S((C, I), b),
        S((L, D), f64) if has_limits else S((1, D), f64),
    )


def maybe_attempts(scheduler) -> Sequence:
    """The attempt list prefix for fused-eligible routing; [] when the
    fused path is off or the solve is topo-routed (metered there)."""
    if not fused_enabled():
        return []
    return [_FusedSolve]
