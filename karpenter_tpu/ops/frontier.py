"""Frontier reductions for the device-resident consolidation search.

The multi-node consolidation search probes prefix sizes of a cost-sorted
candidate list. The sequential reference walks a binary search — one full
scheduling simulation per probe, each bound waiting on the last verdict
(multinodeconsolidation.go:117-170). The frontier search instead evaluates
whole *levels* of that binary decision tree speculatively: every probe the
sequential search *could* reach within the next `depth` verdicts is
simulated as one coalesced solverd batch, then the tree is walked host-side
using the batch's verdicts. Because the probe set of a round is exactly the
top `depth` levels of the sequential search's decision tree rooted at the
current (lo, hi), the walk reproduces the sequential search's probe
sequence — and therefore its decision — *bit for bit*, with no monotonicity
assumption required: rounds shrink from log2(N) sequential simulations to
ceil(log2(N)/depth) batched ones, and the speculative probes it evaluates
are a superset of the probes the sequential search visits.

This module also hosts the prefix-structured price reductions that feed the
per-probe verdicts. The sequential search recomputes candidate prices and
the same-type price floors from scratch for every probe (O(probes x prefix x
offerings)); a frontier evaluates many prefixes of the SAME candidate
order, so both collapse to one pass over the candidates: a sequential
left-fold cumulative sum for prefix prices (np.add.accumulate is an exact
left fold over float64 — bit-identical to the reference's running Python
sum) and a running per-type minimum for the replace-cheaper-than-cheapest
gate (min is exact; order-independent). The k scheduling simulations are
the device batch; these reductions are the O(N) host vector work that turns
their results into per-prefix verdicts without re-walking the prefix.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

# Speculation depth: how many levels of the binary decision tree one
# coalesced batch evaluates. A round of depth d simulates at most 2^d - 1
# prefixes and consumes d sequential verdicts, so the ~7-level search over
# the <=100-candidate window runs in ceil(7/d) rounds. The default is
# deliberately modest: each speculative probe is a real scheduling
# simulation, and only about d of the 2^d - 1 land on the walked path —
# depth 2 triples the per-round batch the coalescer can fuse while keeping
# the speculation waste bounded (~2x the sequential probe count).
DEFAULT_DEPTH = 2


def speculative_probes(lo: int, hi: int, depth: int) -> list[int]:
    """The prefix indices (binary-search mids) in the top `depth` levels of
    the sequential search's decision tree over [lo, hi]. Every interval in
    the tree is disjoint from its siblings, so the mids are distinct; they
    are returned in deterministic preorder."""
    probes: list[int] = []

    def rec(lo: int, hi: int, d: int) -> None:
        if d <= 0 or lo > hi:
            return
        mid = (lo + hi) // 2
        probes.append(mid)
        rec(lo, mid - 1, d - 1)
        rec(mid + 1, hi, d - 1)

    rec(lo, hi, depth)
    return probes


class PrefixPrices:
    """Per-prefix current prices of a fixed candidate order, computed once.

    `get_candidate_prices` (consolidation.go:304-329) scans the candidates
    in order: the first candidate with no compatible current offering
    decides the whole answer — 0.0 when it is reserved capacity, None
    (abort) otherwise; if every candidate is compatible the answer is the
    running sum of the cheapest compatible prices. For a prefix of length m
    that is a pure function of (first bad index, cumulative sum), both of
    which one pass over the candidates yields for ALL prefixes at once."""

    def __init__(self, candidates: Sequence) -> None:
        from karpenter_tpu.apis import labels as wk
        from karpenter_tpu.cloudprovider.types import Offerings
        from karpenter_tpu.scheduling.requirements import Requirements

        n = len(candidates)
        prices = np.zeros(n, dtype=np.float64)
        # index of the first candidate with no compatible offering, and
        # whether that candidate was reserved (-> price 0.0) or not (-> None)
        self._bad_index = n
        self._bad_reserved = False
        for i, c in enumerate(candidates):
            reqs = Requirements.from_labels(c.state_node.labels())
            compatible = Offerings(c.instance_type.offerings).compatible(reqs)
            if not compatible:
                self._bad_index = i
                self._bad_reserved = reqs.get(wk.CAPACITY_TYPE_LABEL_KEY).has(
                    wk.CAPACITY_TYPE_RESERVED
                )
                break
            prices[i] = compatible.cheapest().price
        # exact left fold: np.add.accumulate computes r[i] = r[i-1] + p[i]
        # in candidate order, the same float64 addition sequence as the
        # reference's running `price += ...`
        self._cumulative = np.add.accumulate(prices)

    def for_prefix(self, m: int) -> Optional[float]:
        """The `get_candidate_prices` answer for candidates[:m]."""
        if m <= 0:
            return 0.0
        if self._bad_index < m:
            return 0.0 if self._bad_reserved else None
        return float(self._cumulative[m - 1])


class PrefixTypeFloors:
    """Per-prefix inputs of the replace-cheaper-than-cheapest gate.

    `_filter_out_same_type` (multinodeconsolidation.go:188-226) needs, per
    prefix: the set of instance types the prefix currently runs, and the
    cheapest CURRENT price each of those types runs at. Both are running
    reductions over the candidate order (set union / per-type min), so one
    pass yields every prefix's view; the per-candidate compatible-offering
    scan — the expensive part the sequential search repeats per probe —
    happens exactly once per candidate."""

    def __init__(self, candidates: Sequence) -> None:
        from karpenter_tpu.cloudprovider.types import Offerings
        from karpenter_tpu.scheduling.requirements import Requirements

        # snapshots[m-1] = (existing type names, per-type price floor) for
        # candidates[:m]; the dicts/sets are frozen copies per prefix (a
        # candidate window is <=100, so the copies are trivially small and
        # callers can mutate nothing shared)
        self._snapshots: list[tuple[frozenset, dict]] = []
        types: set[str] = set()
        floors: dict[str, float] = {}
        for c in candidates:
            types.add(c.instance_type.name)
            compatible = Offerings(c.instance_type.offerings).compatible(
                Requirements.from_labels(c.state_node.labels())
            )
            if compatible:
                p = compatible.cheapest().price
                if p < floors.get(c.instance_type.name, math.inf):
                    floors[c.instance_type.name] = p
            self._snapshots.append((frozenset(types), dict(floors)))

    def max_price(self, m: int, option_names: Sequence[str]) -> float:
        """The price cap `_filter_out_same_type` derives for a replacement
        whose instance-type options are `option_names`, against the prefix
        candidates[:m]: the cheapest current price among shared types."""
        if m <= 0 or not self._snapshots:
            return math.inf
        types, floors = self._snapshots[min(m, len(self._snapshots)) - 1]
        max_price = math.inf
        for name in option_names:
            if name in types:
                max_price = min(max_price, floors.get(name, math.inf))
        return max_price
