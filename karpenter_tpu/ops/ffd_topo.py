"""Topology-aware device fast path: grouped FFD for solves with topology
machinery engaged.

The plain device path (ops/ffd.py) declines any solve with topology groups
because topology breaks the monotonicity its caches rely on: a claim that
rejects a pod for skew today may accept it after counts change. This module
extends the grouped simulation to topology-engaged solves — spread, pod
affinity/anti-affinity, and inverse anti-affinity from existing cluster
pods (reference scheduling/topology.go + topologygroup.go:205-408) — while
preserving EXACT host-decision parity:

- Pods collapse into shape groups keyed by the topo-aware signature (spec
  shape + namespace + labels + full constraint content — selectors match on
  labels, so labels are part of identity here, unlike the plain path).
- Groups that own topology groups are VOLATILE: their placements run the
  full host gate sequence per candidate (taints → compat → topology
  next-domain via the real `Topology.add_requirements` → instance-type
  narrowing through the engine's cached row masks). No monotone caching —
  skew rejections are not permanent.
- Plain groups keep the fast monotone path (heaps, family transitions), plus
  a record hook: the host records EVERY placement into any topology group
  whose selector matches the pod (topology.go:252-276), so counts stay
  exact even when only a minority of pods carry constraints.
- Decision-parity traps handled explicitly:
  * hostname placeholders: sorted-domain iteration makes placeholder STRINGS
    decision-relevant (topologygroup.go:269-276 hostname min-count, sorted
    scans), so topo solves draw hostnames from the host scheduler's counter
    (scheduler.nodeclaim._hostname_counter) at the host's exact consumption
    points — one per template attempt that passes the limits gate, matching
    NodeClaim construction in _add_to_new_node_claim (scheduler.go:478-556).
  * relaxation: the ladder (preferences.go) is driven exactly like the host
    — deepcopy, relax one step, topology.update + pod-data refresh, retry —
    with the relaxed copy migrating to its new shape group.
  * rollback: topology counts are snapshotted at solve start and restored if
    the solve aborts (fallback/strict), and relax-touched ownership is reset
    via topology.update(original), so a host fallback never sees device-
    mutated topology state.
"""

from __future__ import annotations

import copy
import heapq
import time
from bisect import bisect_left
from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Pod
from karpenter_tpu.metrics import global_registry
from karpenter_tpu.ops.ffd import (
    _EPS,
    _DeviceSolve,
    _Fallback,
    _Group,
    _IneligibleShape,
    _raw_sig,
)
from karpenter_tpu.ops import topo_counts
from karpenter_tpu.ops.topo_counts import GroupCounts, build_gate
from karpenter_tpu.scheduler import nodeclaim as ncmod
from karpenter_tpu.scheduler.topology import (
    TYPE_AFFINITY,
    TYPE_ANTI_AFFINITY,
    TYPE_SPREAD,
)
from karpenter_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    Operator,
    Requirement,
    Requirements,
)
from karpenter_tpu.scheduling.taints import Taints
from karpenter_tpu.utils import resources as res

_TOPO_SOLVES_CTR = global_registry.counter(
    "karpenter_scheduler_device_topo_solves_total",
    "topology-engaged scheduling solves served by the device fast path",
)

# process-global interning for topo-aware signatures, parallel to
# ffd._SIG_IDS (separate space: the same spec shape means different things
# once labels/constraints matter)
_TSIG_IDS: dict[tuple, int] = {}
_TSIG_CAP = 200_000
_tsig_next = 0


def _intern_tsig(pod: Pod) -> int:
    """Interned topo-signature id for a pod, cached on the object."""
    global _tsig_next
    sig = getattr(pod, "_kt_tsig", None)
    if sig is None:
        raw = _topo_sig(pod)
        sig = _TSIG_IDS.get(raw)
        if sig is None:
            if len(_TSIG_IDS) >= _TSIG_CAP:
                _TSIG_IDS.clear()
            sig = _tsig_next
            _tsig_next += 1
            _TSIG_IDS[raw] = sig
        try:
            pod._kt_tsig = sig
        except Exception:  # noqa: BLE001 — slotted/frozen pod
            pass
    return sig


def supported(scheduler) -> bool:
    """Can this topology-engaged solve run on the device path?

    All group types are handled: spread, pod (anti-)affinity, and inverse
    anti-affinity from existing cluster pods (topology.go:55-58) — groups
    touching a shape make it volatile (full host gate sequence per
    candidate); everything else keeps the fast monotone path. The hook
    remains as the gate point for future unsupported constructs."""
    return True


def _sel_sig(sel) -> Optional[tuple]:
    if sel is None:
        return None
    return (
        tuple(sorted(sel.match_labels.items())),
        tuple(
            (e["key"], e["operator"], tuple(e.get("values", ())))
            for e in sel.match_expressions
        ),
    )


def _aff_term_sig(term) -> tuple:
    return (
        term.topology_key,
        _sel_sig(term.label_selector),
        tuple(term.namespaces),
        _sel_sig(term.namespace_selector),
    )


def _topo_sig(pod: Pod) -> tuple:
    """Shape signature for topology-engaged solves: the plain spec signature
    plus namespace, labels (selector targets), and full constraint content
    (spread, pod (anti-)affinity incl. preferred terms, preferred node
    affinity — all decision-relevant once topology groups exist)."""
    spec = pod.spec
    md = pod.metadata
    tsc = tuple(
        (
            t.topology_key,
            t.max_skew,
            t.when_unsatisfiable,
            _sel_sig(t.label_selector),
            t.min_domains,
            t.node_affinity_policy,
            t.node_taints_policy,
            tuple(t.match_label_keys),
        )
        for t in spec.topology_spread_constraints
    )
    pa_sig: tuple = ()
    panti_sig: tuple = ()
    pref_na_sig: tuple = ()
    aff = spec.affinity
    if aff is not None:
        if aff.pod_affinity is not None:
            pa_sig = (
                tuple(_aff_term_sig(t) for t in aff.pod_affinity.required),
                tuple(
                    (w.weight, _aff_term_sig(w.pod_affinity_term))
                    for w in aff.pod_affinity.preferred
                ),
            )
        if aff.pod_anti_affinity is not None:
            panti_sig = (
                tuple(_aff_term_sig(t) for t in aff.pod_anti_affinity.required),
                tuple(
                    (w.weight, _aff_term_sig(w.pod_affinity_term))
                    for w in aff.pod_anti_affinity.preferred
                ),
            )
        na = aff.node_affinity
        if na is not None and na.preferred:
            pref_na_sig = tuple(
                (
                    w.weight,
                    tuple(
                        (e["key"], e["operator"], tuple(e.get("values", ())))
                        for e in w.preference.match_expressions
                    ),
                )
                for w in na.preferred
            )
    ports_sig = tuple(
        (p.host_port, p.host_ip, p.protocol)
        for c in list(spec.containers) + list(spec.init_containers)
        for p in c.ports
        if p.host_port != 0
    )
    return (
        _raw_sig(pod),
        md.namespace,
        tuple(sorted(md.labels.items())) if md.labels else (),
        tsc,
        pa_sig,
        panti_sig,
        pref_na_sig,
        ports_sig,
    )


def _group_eligible_topo(pod: Pod) -> bool:
    """Per-shape gates for topo mode: every remaining shape feature is
    handled — topology constraints (relax ladder + volatile paths), host
    ports (conflict-tracked), and volumes (per-pod CSI attach-limit checks
    against existing nodes; volume-derived zone requirements were already
    injected by VolumeTopology before the solve)."""
    return True


class _ScanOrder:
    """The host's in-flight claim scan order, maintained incrementally.

    The host stable-sorts claims by pod count before every scan
    (scheduler.go:457-459); (count, rank, ci) reproduces that order exactly
    (see _host_claim_order). Keys are unique (ci tiebreak), so each join is
    one bisect-delete + bisect-insert instead of a full re-sort per attempt."""

    __slots__ = ("keys", "cis")

    def __init__(self):
        self.keys: list[tuple] = []
        self.cis: list[int] = []

    def add(self, ci: int, key: tuple) -> None:
        i = bisect_left(self.keys, key)
        self.keys.insert(i, key)
        self.cis.insert(i, ci)

    def move(self, ci: int, old_key: tuple, new_key: tuple) -> None:
        i = bisect_left(self.keys, old_key)
        del self.keys[i]
        del self.cis[i]
        self.add(ci, new_key)


# sentinel domain in record plans: resolve to the claim's hostname
_HOSTNAME_DOMAIN = object()

# claim-entry kinds in compiled join plans (hostname-keyed groups: the
# domain is the claim's own hostname, so admission is per claim, not per
# family — each collapses to a count lookup against the host dict)
_CE_ANTI = 0  # reject unless domains[hostname] == 0 (topologygroup.go:380-387)
_CE_SPREAD = 1  # admit iff count(+self) <= maxSkew (topologygroup.go:215-227)
_CE_AFFINITY = 2  # HostAffinityGate (count > 0, or gen-cached self-seed)


class _TopoSolve(_DeviceSolve):
    """Grouped FFD with exact topology semantics (Python driver only — the
    native kernel's steady-state caches assume monotone rejections, which
    topology breaks, so topo solves run the instrumented Python loop)."""

    def __init__(self, scheduler, pods: Sequence[Pod]):
        super().__init__(scheduler, pods)
        self.topology = scheduler.topology
        self._sig_to_gi: dict[int, int] = {}
        self.g_volatile: list[bool] = []
        self.g_rec: list[list] = []  # groups whose selector matches the shape
        self.g_matched: list[list] = []  # owned + inverse-selected, host order
        self.g_inv_owned: list[list] = []  # inverse groups the shape owns
        self.g_relaxable: list[bool] = []
        self.g_rep: list[Pod] = []  # shape representative (for meta refresh)
        self.g_ports: list[list] = []  # host ports per shape (usually empty)
        self._any_ports = False  # _claim_hp (base class) tracked when True
        self.g_volumes: list[bool] = []  # shape has PVC-backed volumes
        self._any_volumes = False
        self._known_tg_count = len(self.topology.topology_groups) + len(
            self.topology.inverse_topology_groups
        )
        self._hn_tgs = [
            tg
            for tg in (
                list(self.topology.topology_groups.values())
                + list(self.topology.inverse_topology_groups.values())
            )
            if tg.key == wk.LABEL_HOSTNAME
        ]
        self._hostname_tgs = bool(self._hn_tgs)
        self._saved_topology: Optional[tuple] = None
        self._saved_node_usage: list[tuple] = []
        self._relax_restore: dict[str, Pod] = {}
        self._aborted = False
        self._scan = _ScanOrder()
        # steady-state fast-join plans per (fam, gi): None = slow path
        self._join_plans: dict[tuple[int, int], Optional[list]] = {}
        # record plans per (gi, ti, fam)
        self._rec_plans: dict[tuple[int, int, int], tuple] = {}
        # -- device count-tensor state (ops/topo_counts.py) -----------------
        # count tensors per live TopologyGroup (keyed by object identity;
        # groups outlive the solve via the topology dicts / snapshot)
        self._tg_counts: dict[int, GroupCounts] = {}
        # compiled admission gates per (gi, topology group): the pod-domain
        # row and self-selection are shape-static, so one gate serves every
        # family/claim probe of the pair
        self._gates: dict[tuple[int, int], object] = {}
        # fam-level admission verdicts per (gi, fam), validated against the
        # matched groups' count generations: (ok, gen0, gen1, ...) — a probe
        # between placements is a dict hit plus integer compares
        self._fam_adm: dict[tuple[int, int], tuple] = {}
        # claim-opening memo per shape group: (tokens, gens, outcomes) —
        # the host template loop replayed as placeholder draws + a cached
        # opening while the matched groups' count generations stand still
        # (see _new_claim_topo)
        self._open_memo: dict[int, tuple] = {}
        self._fresh_hostnames_safe = False
        # monotone-scan classification per shape group (None = undecided):
        # True when every matched topology group is hostname anti-affinity
        # and no per-candidate state accumulates (ports/volumes/hostname/
        # strict-reserved) — then ALL rejection reasons are permanent and
        # the claim scan runs over a lazily-synced heap with pop-on-reject,
        # killing the O(pods x claims) probe on anti-affinity-heavy solves
        self.g_mono: list[Optional[bool]] = []
        # hostname-group-set epoch for once-per-claim hostname registration
        self._hn_epoch = 0

    # -- incremental host scan order ----------------------------------------

    def _order_hook_add(self, ci: int) -> None:
        c = self.claims[ci]
        self._scan.add(ci, (c.count, c.rank, ci))

    def _order_hook_move(self, ci: int, old_key: tuple, new_key: tuple) -> None:
        self._scan.move(ci, old_key, new_key)

    # -- grouping -----------------------------------------------------------

    def _group_pods(self) -> Optional[np.ndarray]:
        pods = self.pods
        # warm fast path: pods persist across provisioner passes and carry
        # their interned topo-signature (mirrors ffd._group_pods)
        try:
            sigs = np.asarray([p._kt_tsig for p in pods], dtype=np.int64)
        except AttributeError:
            sigs = np.empty(len(pods), dtype=np.int64)
            for i, pod in enumerate(pods):
                sigs[i] = _intern_tsig(pod)
        _, first_idx, inverse, counts = np.unique(
            sigs, return_index=True, return_inverse=True, return_counts=True
        )
        for k, fi in enumerate(first_idx):
            pod = pods[int(fi)]
            gi = self._build_group(pod)
            if gi is None:
                return None
            self.groups[gi].n_pods = int(counts[k])
            self._sig_to_gi[int(sigs[int(fi)])] = gi
        return inverse.astype(np.int32)

    def _build_group(self, pod: Pod) -> Optional[int]:
        """Create the shape group for `pod` (its signature's representative);
        returns the group index, or None when the shape is ineligible."""
        s, dims = self.s, self.dims
        if not _group_eligible_topo(pod):
            return None
        s.update_cached_pod_data(pod)
        data = s.cached_pod_data[pod.metadata.uid]
        if any(name not in dims for name in data.requests):
            return None
        group = _Group(data, dims)
        # hostname-constrained shapes are handled VOLATILE: the claim scan
        # gates on the pod's hostname row against each claim's placeholder
        # (can_add's compat rejection, nodeclaim.go:285-291), and new-claim
        # attempts reproduce the host's compat error with the exact consumed
        # placeholder string — this driver draws from the host's counter, so
        # even pathological selectors naming placeholder strings behave
        # identically to a pure host run
        group.rowset = self._rows_sans_hostname(group.reqs)
        gi = len(self.groups)
        self.groups.append(group)
        self.gheaps.append([])
        self.gsynced.append(0)
        self.nptr.append(0)
        # SNAPSHOT the representative: a mid-relax pod keeps mutating in
        # place on later rungs, and _maybe_refresh_groups recomputes this
        # group's topology metadata from its rep — a live reference would
        # silently shift the group onto the FUTURE shape's topology groups
        # (soak seed 101: a wildcard-toleration rung re-pointed a pre-relax
        # group at a fresh-count spread group, admitting an over-skew join)
        self.g_rep.append(copy.deepcopy(pod))
        self.g_relaxable.append(self._shape_relaxable(pod))
        from karpenter_tpu.scheduling.hostportusage import get_host_ports

        ports = get_host_ports(pod)
        self.g_ports.append(ports)
        if ports:
            self._any_ports = True
        has_volumes = bool(getattr(pod.spec, "volumes", None))
        self.g_volumes.append(has_volumes)
        if has_volumes:
            self._any_volumes = True
        self._append_group_meta(pod, ports, has_volumes, group.has_hostname)
        return gi

    def _append_group_meta(
        self, pod: Pod, ports: list, has_volumes: bool, has_hostname: bool
    ) -> None:
        """Per-shape topology metadata (also recomputed by
        _maybe_refresh_groups when relaxation creates new groups mid-solve)."""
        topo = self.topology
        owned = self._shape_owned(pod)
        # inverse groups match via counts() = selects() (their node filter is
        # the permissive zero value, topologynodefilter.go:27-40) — a shape
        # an existing pod's anti-affinity selector matches is volatile too;
        # host-port, volume, and hostname-constrained shapes are volatile
        # too (their admission state accumulates per candidate / is per-pod)
        inv_matched = [
            tg for tg in topo.inverse_topology_groups.values() if tg.selects(pod)
        ]
        self.g_volatile.append(
            bool(
                owned
                or inv_matched
                or ports
                or has_volumes
                or has_hostname
                # strict reserved: every join runs the reservation gate at
                # the host's can_add position, and its rejections are not
                # monotone (capacity frees on release)
                or self.strict_res
            )
        )
        # host matching order: owned groups in dict order, then matching
        # inverse groups (topology.py _matching_topologies)
        matched = owned + inv_matched
        self.g_matched.append(matched)
        self.g_rec.append(
            [tg for tg in topo.topology_groups.values() if tg.selects(pod)]
        )
        self.g_inv_owned.append(
            [
                tg
                for tg in topo.inverse_topology_groups.values()
                if tg.is_owned_by(pod.metadata.uid)
            ]
        )
        # monotone classification: hostname anti-affinity counts only grow
        # during a solve, so every rejection reason on the claim scan is
        # permanent and the scan can pop claims from a per-group heap
        self.g_mono.append(
            bool(matched)
            and not ports
            and not has_volumes
            and not has_hostname
            and not self.strict_res
            and all(
                tg.type == TYPE_ANTI_AFFINITY and tg.key == wk.LABEL_HOSTNAME
                for tg in matched
            )
        )

    def _shape_owned(self, pod: Pod) -> list:
        """Groups a pod of this shape owns, derived from the topology
        engine's shape memo (value identity) rather than per-uid ownership —
        per-uid state is transiently wrong for the pod currently mid-relax.
        Returned in topology_groups dict order (the host's matching order)."""
        from karpenter_tpu.scheduler.topology import _pod_shape_key

        topo = self.topology
        memo = topo._shape_groups.get(_pod_shape_key(pod))
        if memo is None:
            # shape never passed through update() — pods without topology
            # constraints own nothing
            if pod.spec.topology_spread_constraints or pod.spec.affinity is not None:
                uid = pod.metadata.uid
                return [
                    tg for tg in topo.topology_groups.values() if tg.is_owned_by(uid)
                ]
            return []
        owned_ids = set(map(id, memo))
        return [tg for tg in topo.topology_groups.values() if id(tg) in owned_ids]

    def _maybe_refresh_groups(self) -> None:
        """Relaxation's topology.update can CREATE topology groups mid-solve
        (a relaxed shape's node-filter hash differs): the host records
        subsequent placements into them, so every per-shape list and compiled
        plan must be rebuilt to include them."""
        topo = self.topology
        n = len(topo.topology_groups) + len(topo.inverse_topology_groups)
        if n == self._known_tg_count:
            return
        self._known_tg_count = n
        self._hn_tgs = [
            tg
            for tg in (
                list(topo.topology_groups.values())
                + list(topo.inverse_topology_groups.values())
            )
            if tg.key == wk.LABEL_HOSTNAME
        ]
        self._hostname_tgs = bool(self._hn_tgs)
        # claims lazily re-register their hostnames into the grown group set
        # on their next join (the host registers on every NodeClaim.add, so a
        # claim that never joins again never registers — epoch-lazy matches)
        self._hn_epoch += 1
        self.g_volatile.clear()
        self.g_matched.clear()
        self.g_rec.clear()
        self.g_inv_owned.clear()
        self.g_mono.clear()
        for rep, ports, has_vols, group in zip(
            self.g_rep, self.g_ports, self.g_volumes, self.groups
        ):
            self._append_group_meta(rep, ports, has_vols, group.has_hostname)
        self._rec_plans.clear()
        self._join_plans.clear()
        self._fam_adm.clear()
        self._open_memo.clear()
        # matched sets (and volatility itself) may have changed: rebuild
        # every group's claim heap from scratch so claims popped under the
        # OLD gates are re-probed under the new ones (plain-path drops are
        # re-derived from the per-claim gdrop sets on the first rescan)
        for gi in range(len(self.gheaps)):
            self.gheaps[gi] = []
            self.gsynced[gi] = 0
        # (no snapshot extension needed: abort() restores the pre-solve group
        # DICTS, discarding mid-solve-created groups entirely)

    def _shape_relaxable(self, pod: Pod) -> bool:
        """Does the relaxation ladder (preferences.go:33-145) have anything
        to remove for this shape? Mirrors Preferences.relax applicability."""
        spec = pod.spec
        aff = spec.affinity
        if aff is not None:
            na = aff.node_affinity
            if na is not None and (na.preferred or len(na.required) > 1):
                return True
            if aff.pod_affinity is not None and aff.pod_affinity.preferred:
                return True
            if aff.pod_anti_affinity is not None and aff.pod_anti_affinity.preferred:
                return True
        if any(
            t.when_unsatisfiable == "ScheduleAnyway"
            for t in spec.topology_spread_constraints
        ):
            return True
        if self.s.preferences.tolerate_prefer_no_schedule:
            # the ladder's final rung adds a wildcard PreferNoSchedule
            # toleration (preferences.go:133-145) unless already present
            for t in spec.tolerations:
                if (
                    t.operator == "Exists"
                    and t.effect == "PreferNoSchedule"
                    and t.key == ""
                    and t.value == ""
                ):
                    return False
            return True
        return False

    def _ensure_group(self, pod: Pod) -> Optional[int]:
        """Group index for a relaxed copy, creating its shape group lazily.
        cached_pod_data[uid] was already refreshed by the caller (mirroring
        the host's update_cached_pod_data after relax)."""
        sig = _intern_tsig(pod)
        gi = self._sig_to_gi.get(sig)
        if gi is None:
            gi = self._build_group(pod)
            if gi is None:
                return None
            self._sig_to_gi[sig] = gi
        return gi

    # -- topology state management ------------------------------------------

    def _snapshot_topology(self) -> None:
        # counts + group dicts via the engine's snapshot/rollback contract
        # (scheduler/topology.py): a restore also stamps fresh count
        # generations, so device count tensors can never alias rolled-back
        # state
        self._saved_topology = self.topology.snapshot_counts()
        # Freshly drawn hostname placeholders have occupancy 0 in every
        # hostname group UNLESS the cluster pathologically contains
        # placeholder-shaped domains already (store pods / node names):
        # every placeholder recorded mid-solve comes from the monotonic
        # counter and is strictly older than any future draw. The flag
        # gates the claim-opening memo's hostname-freshness assumption.
        self._fresh_hostnames_safe = not any(
            d.startswith("hostname-placeholder-")
            for tg in self._hn_tgs
            for d in tg.domains
        )
        # port/volume joins fork usage onto the ExistingNode (copy-on-write
        # — the StateNode itself is never written); a fallback must still
        # not leave phantom fork entries behind for the host loop to read
        if self._any_ports or self._any_volumes:
            self._saved_node_usage = [
                (nd.en, nd.en.usage_snapshot()) for nd in self.nodes
            ]

    def abort(self) -> None:
        """Restore topology to its pre-solve state so the host fallback runs
        against uncorrupted counts, ownership, and group sets."""
        if self._aborted:
            return
        self._aborted = True
        self._restore_rm()
        topo = self.topology
        if self._saved_topology is not None:
            topo.restore_counts(self._saved_topology)
        for en, usage in self._saved_node_usage:
            en.restore_usage(usage)
        for orig in self._relax_restore.values():
            topo.update(orig)
            self.s.update_cached_pod_data(orig)
        self._relax_restore.clear()

    # -- record hooks (NodeClaim.add / ExistingNode.add tails) ---------------

    def _needs_record(self, gi: int) -> bool:
        # only reached on non-volatile branches; inverse-group OWNERS have
        # required anti-affinity and thus own a regular group too → volatile,
        # so inverse record bookkeeping never needs gating here
        return bool(self.g_rec[gi]) or self._hostname_tgs

    # -- record plans (NodeClaim.add tail, nodeclaim.go:324-346) -------------
    #
    # The host registers the claim hostname and records into every group
    # whose selector matches the pod and whose node filter admits the claim.
    # For claims all inputs are (shape, template, family)-determined: selects
    # is per shape (g_rec), the node filter per (group, taints, family), and
    # the recorded domain per family row (or the claim's hostname). The plan
    # compiles that once; applying it is a handful of dict increments.

    def _build_rec_plan(self, gi: int, ti: int, fam: int) -> tuple:
        """Entries carry the group's count tensor directly (created on
        first record if the group has none yet) so applying a plan is a
        straight-line scatter into tensor + host dict per entry."""
        reqs = self.fam_reqs[fam]
        taints = self.s.nodeclaim_templates[ti].spec.taints
        entries: list[tuple] = []
        for tg in self.g_rec[gi]:
            if not tg.node_filter.matches(
                taints, reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
            ):
                continue
            if tg.key == wk.LABEL_HOSTNAME:
                # the claim's hostname row is always single-valued. Hostname
                # groups stay dict-backed (their gates are single lookups and
                # per-claim registrations would churn a tensor), so the entry
                # carries the group itself — record() has the same shape.
                entries.append((tg, _HOSTNAME_DOMAIN))
                continue
            row = reqs.get(tg.key) if reqs.has(tg.key) else None
            if tg.type == TYPE_ANTI_AFFINITY:
                vals = tuple(row.values_list()) if row is not None else ()
                if vals:
                    entries.append((self._group_counts(tg), vals))
            elif row is not None and not row.complement and len(row.values) == 1:
                entries.append((self._group_counts(tg), next(iter(row.values))))
        inv: list[tuple] = []
        for tg in self.g_inv_owned[gi]:
            if tg.key == wk.LABEL_HOSTNAME:
                inv.append((tg, _HOSTNAME_DOMAIN))
                continue
            row = reqs.get(tg.key) if reqs.has(tg.key) else None
            vals = tuple(row.values_list()) if row is not None else ()
            if vals:
                inv.append((self._group_counts(tg), vals))
        plan = (entries, inv)
        self._rec_plans[(gi, ti, fam)] = plan
        return plan

    def _apply_record_plan(self, gi: int, c) -> None:
        if self._hostname_tgs and c.hn_epoch != self._hn_epoch:
            # register once per (claim, hostname-group-set epoch): the host
            # registers on every NodeClaim.add, but registration of a known
            # domain is a no-op, and hostnames are never unregistered
            # mid-solve — so the first registration per epoch is exact
            for tg in self._hn_tgs:
                tg.register(c.hostname)
            c.hn_epoch = self._hn_epoch
        plan = self._rec_plans.get((gi, c.ti, c.fam))
        if plan is None:
            plan = self._build_rec_plan(gi, c.ti, c.fam)
        entries, inv = plan
        for gc, dom in entries:
            if dom is _HOSTNAME_DOMAIN:
                gc.record(c.hostname)
            elif type(dom) is tuple:
                gc.record(*dom)
            else:
                gc.record(dom)
        for gc, vals in inv:
            if vals is _HOSTNAME_DOMAIN:
                gc.record(c.hostname)
            else:
                gc.record(*vals)

    # -- volatile paths ------------------------------------------------------

    def _try_nodes_topo(self, pod: Pod, g: _Group, gi: int) -> bool:
        """Existing-node scan for topology-owning shapes: full rescan in host
        order every attempt (skew admission is not monotone), the real
        Topology.add_requirements in the gate sequence
        (existingnode.go:63-101)."""
        topo = self.topology
        gp = self.g_ports[gi]
        vols = None
        if self.g_volumes[gi]:
            from karpenter_tpu.scheduling.volumeusage import get_volumes

            vols = get_volumes(self.s.store, pod)
        for nd in self.nodes:
            tol = nd.gtol.get(gi)
            if tol is None:
                tol = Taints(nd.en.cached_taints).tolerates_pod(pod) is None
                nd.gtol[gi] = tol
            if not tol:
                continue
            if (
                vols is not None
                and nd.en.volume_usage.exceeds_limits(vols) is not None
            ):
                continue
            if gp and nd.en.hostport_usage.conflicts(pod, gp) is not None:
                continue
            kc = nd.gcap.get(gi)
            if kc is None or kc[0] != nd.usage_ver:
                k = self._node_capacity(nd, g)
                nd.gcap[gi] = (nd.usage_ver, k)
            else:
                k = kc[1]
            if k <= 0:
                continue
            cc = nd.gcompat.get(gi)
            if cc is None or cc[0] != nd.version:
                ok = nd.reqs.compatible(g.reqs) is None
                nd.gcompat[gi] = (nd.version, ok)
            else:
                ok = cc[1]
            if not ok:
                continue
            joint = Requirements(*nd.reqs.values())
            joint.add(*g.reqs.values())
            try:
                topo_reqs = topo.add_requirements(
                    pod, nd.en.cached_taints, g.strict_reqs, joint
                )
            except ValueError:
                continue
            if joint.compatible(topo_reqs) is not None:
                continue
            joint.add(*topo_reqs.values())
            nd.joined.append(pod)
            nd.remaining = res.subtract(nd.remaining, g.requests)
            nd.reqs = joint
            nd.version += 1
            nd.usage_ver += 1
            topo.record(pod, nd.en.cached_taints, joint)
            if gp:
                nd.en.fork_usage()
                nd.en.hostport_usage.add(pod, gp)
            if vols is not None:
                nd.en.fork_usage()
                nd.en.volume_usage.add(pod, vols)
            return True
        return False

    # -- steady-state fast joins --------------------------------------------
    #
    # When a group's rows are subsumed by the claim family (_SAME) and every
    # matched topology group's key has a single-valued family row (or is the
    # hostname), the full host evaluation collapses: admission is a read
    # against the group's device count tensor (ops/topo_counts.py) — the
    # same verdict tg.get() would compute, served from a masked reduction
    # cached per count generation — and admission implies the joint is
    # unchanged (chosen ∋ v ⇒ {v} ∩ chosen = {v}), so no Requirements are
    # built at all. Rejection is exact too: chosen missing v is precisely
    # the host's compatibility error (or the empty-domain raise). Anything
    # else takes the slow path below, which calls the real host oracle
    # (Topology.add_requirements) and mirrors nodeclaim.go:114-163 verbatim.

    def _group_counts(self, tg) -> GroupCounts:
        gc = self._tg_counts.get(id(tg))
        if gc is None:
            gc = self._tg_counts[id(tg)] = GroupCounts(tg)
        return gc

    def _gate(self, gi: int, tg, pod_dom):
        """Compiled count-tensor admission gate per (shape group, topology
        group) — the pod-domain row and self-selection are shape-static."""
        key = (gi, id(tg))
        gate = self._gates.get(key)
        if gate is None:
            rep = self.g_rep[gi]
            gate = build_gate(
                self._group_counts(tg), pod_dom, tg.selects(rep), rep
            )
            self._gates[key] = gate
        return gate

    def _host_aff_gate(self, gi: int, tg, pod_dom):
        key = ("hn", gi, id(tg))
        gate = self._gates.get(key)
        if gate is None:
            gate = topo_counts.HostAffinityGate(
                tg, pod_dom, tg.selects(self.g_rep[gi])
            )
            self._gates[key] = gate
        return gate

    def _build_join_plan(self, fam: int, gi: int):
        """Compiled plan split into FAM-LEVEL entries (single-valued family
        rows — the verdict is identical for every claim of the family, so
        one gen-cached gate read serves the whole scan) and PER-CLAIM
        entries (hostname ops, which read the claim's own hostname).
        Returns (fam_entries, claim_entries) or None."""
        reqs = self.fam_reqs[fam]
        g = self.groups[gi]
        fam_entries: list[tuple] = []
        claim_entries: list[tuple] = []
        plan = (fam_entries, claim_entries)
        for tg in self.g_matched[gi]:
            pod_dom = g.strict_reqs.get(tg.key)
            if tg.key == wk.LABEL_HOSTNAME:
                if tg.type == TYPE_ANTI_AFFINITY:
                    claim_entries.append((_CE_ANTI, tg, 0))
                elif tg.type == TYPE_SPREAD:
                    s = 1 if tg.selects(self.g_rep[gi]) else 0
                    claim_entries.append((_CE_SPREAD, tg, s))
                else:
                    claim_entries.append(
                        (_CE_AFFINITY, self._host_aff_gate(gi, tg, pod_dom), 0)
                    )
                continue
            row = reqs.get(tg.key) if reqs.has(tg.key) else None
            if row is None or row.complement or len(row.values) != 1:
                plan = None
                break
            z = next(iter(row.values))
            gate = self._gate(gi, tg, pod_dom)
            fam_entries.append((gate, gate.intern(z), z, row, tg))
        self._join_plans[(fam, gi)] = plan
        return plan

    def _fam_admission(self, gi: int, fam: int, fam_entries: list) -> bool:
        """Fam-level verdict over the compiled gates, cached per (gi, fam)
        and validated against the matched groups' count generations — the
        probe between two placements is a dict hit plus an integer compare.
        Single-gate fams (the dominant case) store a flat (ok, gen, tg)
        triple; multi-gate fams a (ok, None, entries, gens) record."""
        akey = (gi, fam)
        cached = self._fam_adm.get(akey)
        if cached is not None:
            tg0 = cached[1]
            if tg0 is not None:  # flat single-gate form
                if cached[2] == tg0._gen:
                    return cached[0]
            else:
                entries, gens = cached[3], cached[4]
                k = 0
                for entry in entries:
                    if gens[k] != entry[4]._gen:
                        break
                    k += 1
                else:
                    return cached[0]
        ok = True
        for gate, zid, z, row, _tg in fam_entries:
            if type(gate) is topo_counts.AffinityGate:
                good = gate.ok_with_row(zid, z, row)
            else:
                good = gate.ok(zid)
            if not good:
                ok = False
                break
        if len(fam_entries) == 1:
            tg0 = fam_entries[0][4]
            self._fam_adm[akey] = (ok, tg0, tg0._gen, fam_entries)
        else:
            self._fam_adm[akey] = (
                ok,
                None,
                None,
                fam_entries,
                tuple(e[4]._gen for e in fam_entries),
            )
        return ok

    def _commit_join(self, c, ci: int, pod: Pod, g: _Group, gi: int, fitrows) -> None:
        """Join tail shared by fast and slow paths: usage grows, rows that
        stop fitting die forever, scan order updated."""
        if fitrows.all():
            c.rem = c.rem - g.req_f
        else:
            c.rem = c.rem[fitrows] - g.req_f
            c.u_ids = c.u_ids[fitrows]
        old_key = (c.count, c.rank, ci)
        c.count += 1
        self.seq += 1
        c.rank = -self.seq
        c.members.append(pod)
        c.group_counts[gi] = c.group_counts.get(gi, 0) + 1
        self._scan.move(ci, old_key, (c.count, c.rank, ci))
        if self.res_active:
            self._apply_reserved(c, self._pending_reserved)
            self._pending_reserved = None

    def _probe_claim(self, pod: Pod, g: _Group, gi: int, c, ci: int) -> bool:
        """One host can_add evaluation of claim `ci` for `pod`
        (nodeclaim.go:114-163), committing the join on success. Under a
        monotone-classified group (g_mono) every False returned here is a
        PERMANENT rejection — the callers rely on that to pop claims."""
        templates = self.s.nodeclaim_templates
        tol = self.tg_tol.get((c.ti, gi))
        if tol is None:
            tol = Taints(templates[c.ti].spec.taints).tolerates_pod(pod) is None
            self.tg_tol[(c.ti, gi)] = tol
        if not tol:
            return False
        gp = self.g_ports[gi]
        # host ports (nodeclaim.go:280-283): conflicts against the claim's
        # accumulated usage reject this candidate
        if gp and self._claim_hp[ci].conflicts(pod, gp) is not None:
            return False
        # hostname-constrained shapes: the host's compat gate sees the
        # claim's placeholder hostname row vs the pod's hostname row
        # (nodeclaim.go:285-291) — reject unless the placeholder satisfies
        # the pod's requirement (NotIn rows usually pass, In[real] never do)
        if g.has_hostname and not g.reqs.get(wk.LABEL_HOSTNAME).has(c.hostname):
            return False
        ent = self.fam_join.get((c.fam, gi))
        if ent is None:
            ent = self._build_fam_join(c.fam, gi)
        if ent[0] == self._REJECT:
            return False
        if ent[0] == self._SAME:
            plan = self._join_plans.get((c.fam, gi), self._MISSING)
            if plan is self._MISSING:
                plan = self._build_join_plan(c.fam, gi)
            if plan is not None:
                fam_entries, claim_entries = plan
                # fam-level gates: one gen-validated tensor read serves
                # every claim of the family until a count changes
                if fam_entries and not self._fam_admission(gi, c.fam, fam_entries):
                    return False
                h = c.hostname
                for kind, obj, s in claim_entries:
                    if kind == _CE_ANTI:
                        # "no matching pod on this host yet"
                        # (topologygroup.go:380-387 fast path)
                        if obj.domains.get(h, 0) != 0:
                            return False
                    elif kind == _CE_SPREAD:
                        # hostname spread fast path: a fresh hostname is
                        # always a valid new domain (min count 0), so the
                        # bound is count(+self) <= maxSkew
                        # (topologygroup.go:215-227, 269-273)
                        if obj.domains.get(h, 0) + s > obj.max_skew:
                            return False
                    elif not obj.ok(h):  # _CE_AFFINITY
                        return False
                d = c.defer
                if d is not None:
                    # deferred fast commit: any-fit over the OPEN-time
                    # pareto rows against accumulated usage (row pruning
                    # telescopes — see _Claim.defer); no row arrays touched
                    pareto, extra = d
                    floor = g.floor_list
                    nd_ = len(floor)
                    for row in pareto:
                        k = 0
                        while k < nd_ and row[k] - extra[k] >= floor[k]:
                            k += 1
                        if k == nd_:
                            break
                    else:
                        return False
                    req = g.req_list
                    for k in range(nd_):
                        extra[k] += req[k]
                    old_key = (c.count, c.rank, ci)
                    c.count += 1
                    self.seq += 1
                    c.rank = -self.seq
                    c.members.append(pod)
                    c.group_counts[gi] = c.group_counts.get(gi, 0) + 1
                    self._scan.move(ci, old_key, (c.count, c.rank, ci))
                    self._apply_record_plan(gi, c)
                    if gp:
                        self._claim_hp[ci].add(pod, gp)
                    return True
                fitrows = (c.rem >= g.fit_floor).all(axis=1)
                if not fitrows.any():
                    return False
                if (
                    self.min_active
                    and not fitrows.all()
                    and not self._min_join_ok(c, c.u_ids[fitrows])
                ):
                    return False
                if self.strict_res:
                    # host can_add position: a ReservedOfferingError here
                    # rejects THIS candidate only — the inflight scan
                    # swallows per-candidate errors (scheduler.go:519-534)
                    try:
                        self._pending_reserved = self._reserved_eval(
                            c.hostname,
                            self.fam_reqs[c.fam],
                            self._final_types(c.type_mask, c.u_ids[fitrows]),
                            fam=c.fam,
                            current_reserved=c.reserved,
                        )
                    except ncmod.ReservedOfferingError:
                        return False
                self._commit_join(c, ci, pod, g, gi, fitrows)
                self._apply_record_plan(gi, c)
                if gp:
                    self._claim_hp[ci].add(pod, gp)
                return True
        # slow path: full host gate sequence with real Requirements.
        # joint BEFORE topology = claim reqs + pod reqs, hostname row
        # included (nodeclaim.go:285-291)
        if c.defer is not None:
            self._materialize(c)
        topo = self.topology
        base = self.fam_reqs[c.fam] if ent[0] == self._SAME else ent[3]
        joint = Requirements(*base.values())
        joint.add(Requirement(wk.LABEL_HOSTNAME, Operator.IN, [c.hostname]))
        try:
            topo_reqs = topo.add_requirements(
                pod,
                templates[c.ti].spec.taints,
                g.strict_reqs,
                joint,
                ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
            )
        except ValueError:
            return False
        if joint.compatible(topo_reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS) is not None:
            return False
        joint.add(*topo_reqs.values())
        final_rows = self._rows_sans_hostname(joint)
        if final_rows == self.fam_rows[c.fam]:
            fitrows = (c.rem >= g.fit_floor).all(axis=1)
            if not fitrows.any():
                return False
            if (
                self.min_active
                and not fitrows.all()
                and not self._min_join_ok(c, c.u_ids[fitrows])
            ):
                return False
            if self.strict_res:
                try:
                    # rows unchanged ⟹ content equals the fam's — the
                    # (fam, offering) compat memo applies
                    self._pending_reserved = self._reserved_eval(
                        c.hostname,
                        joint,
                        self._final_types(c.type_mask, c.u_ids[fitrows]),
                        fam=c.fam,
                        current_reserved=c.reserved,
                    )
                except ncmod.ReservedOfferingError:
                    return False
        else:
            compat_v, offer_v = self._joint_masks(final_rows, joint)
            new_mask = c.type_mask & compat_v & offer_v
            surv_u = np.zeros(self.U, dtype=bool)
            surv_u[self.uid_of_type[new_mask]] = True
            keep = surv_u[c.u_ids]
            fitrows = keep & (c.rem >= g.fit_floor).all(axis=1)
            if not fitrows.any():
                return False
            if self.min_active and not self._min_join_ok(
                c, c.u_ids[fitrows], new_mask
            ):
                return False
            if self.strict_res:
                try:
                    self._pending_reserved = self._reserved_eval(
                        c.hostname,
                        joint,
                        self._final_types(new_mask, c.u_ids[fitrows]),
                        current_reserved=c.reserved,
                    )
                except ncmod.ReservedOfferingError:
                    return False
            c.type_mask = new_mask
            c.rem = c.rem[keep]
            c.u_ids = c.u_ids[keep]
            c.fam = self._intern_fam(final_rows, self._sans_hostname(joint))
            fitrows = fitrows[keep]
        self._commit_join(c, ci, pod, g, gi, fitrows)
        self._apply_record_plan(gi, c)
        if gp:
            self._claim_hp[ci].add(pod, gp)
        return True

    def _try_claims_topo(self, pod: Pod, g: _Group, gi: int) -> bool:
        if self.g_mono[gi]:
            return self._try_claims_mono(pod, g, gi)
        # general scan: skew/affinity admission is not monotone (counts
        # elsewhere can re-admit a claim), so every attempt rescans the
        # in-flight claims in host order. Claims whose family is CACHED
        # inadmissible (and whose gate generations haven't moved) are
        # skipped without paying the probe.
        claims = self.claims
        cis = self._scan.cis
        fam_adm = self._fam_adm
        i = 0
        n = len(cis)
        while i < n:
            ci = cis[i]
            i += 1
            c = claims[ci]
            cached = fam_adm.get((gi, c.fam))
            if cached is not None:
                # resolve the fam verdict HERE (re-evaluating stale entries
                # through the count gates) so inadmissible claims skip the
                # whole probe prefix; the probe's own check then hits warm.
                # Only the flat single-gate fresh path is decoded inline —
                # everything else defers to _fam_admission, the one place
                # that understands the cache layout.
                tg0 = cached[1]
                if tg0 is not None and cached[2] == tg0._gen:
                    ok = cached[0]
                else:
                    ok = self._fam_admission(gi, c.fam, cached[3])
                if not ok:
                    continue
            if self._probe_claim(pod, g, gi, c, ci):
                return True
        return False

    def _try_claims_mono(self, pod: Pod, g: _Group, gi: int) -> bool:
        """Monotone claim scan: every matched group is hostname
        anti-affinity, whose domains only fill during a solve — so every
        rejection reason in the probe (tolerance, family compat, the
        anti-affinity count, fit, minValues) is permanent, and the scan can
        pop rejected claims from a lazily-synced (count, rank, ci) heap
        exactly like the plain driver's _try_claims. This turns the
        O(pods x claims) probe storm on anti-affinity-heavy solves into
        O(pods + claims) amortized, with the same first-admitting claim as
        the host's full rescan."""
        claims = self.claims
        heap = self.gheaps[gi]
        synced = self.gsynced[gi]
        if synced < len(claims):
            for ci in range(synced, len(claims)):
                c = claims[ci]
                heapq.heappush(heap, (c.count, c.rank, ci))
            self.gsynced[gi] = len(claims)
        while heap:
            count, rank, ci = heap[0]
            c = claims[ci]
            if c.count != count or c.rank != rank:
                heapq.heapreplace(heap, (c.count, c.rank, ci))
                continue
            if self._probe_claim(pod, g, gi, c, ci):
                return True
            heapq.heappop(heap)
        return False

    def _open_memo_tokens(self, gi: int) -> Optional[list]:
        """Topology groups whose count generations validate a memoized
        opening of shape group `gi`, or None when the opening is
        memo-ineligible. Hostname spread/anti groups contribute no token:
        their verdict on a FRESH placeholder (occupancy 0) is structurally
        count-independent — guarded by the freshness flag. Hostname
        affinity groups and every non-hostname group are gen-tracked."""
        if self.strict_res or self.res_active or self.groups[gi].has_hostname:
            return None
        toks: list = []
        for tg in self.g_matched[gi]:
            if tg.key == wk.LABEL_HOSTNAME and tg.type != TYPE_AFFINITY:
                if not self._fresh_hostnames_safe:
                    return None
            else:
                toks.append(tg)
        return toks

    def _replay_open(self, pod: Pod, gi: int, outcomes: list) -> None:
        """Replay a validated opening: consume one placeholder per failing
        template attempt (host parity — the counter advances on every
        retry) and open the memoized claim on the successful one."""
        s = self.s
        for out in outcomes:
            if out is None:  # template attempt that drew and failed
                next(ncmod._hostname_counter)
                continue
            ti, fam, candidate, u_ids, rem0_fit, min_specs, min_relaxed = out
            hostname = f"hostname-placeholder-{next(ncmod._hostname_counter):04d}"
            self._open_claim(
                ti, fam, pod, gi, candidate, u_ids, rem0_fit.copy(),
                hostname=hostname, min_specs=min_specs, min_relaxed=min_relaxed,
                pareto=self._pareto_for(rem0_fit) if self._defer_ok else None,
            )
            if self._any_ports:
                nct = s.nodeclaim_templates[ti]
                gp = self.g_ports[gi]
                hp = s.daemon_hostports[nct].copy()
                if gp:
                    hp.add(pod, gp)
                self._claim_hp[len(self.claims) - 1] = hp
            self._apply_record_plan(gi, self.claims[-1])
            # no _subtract_max: memo eligibility requires limitless pools

    def _new_claim_topo(self, pod: Pod, g: _Group, gi: int) -> Optional[Exception]:
        """New-claim opening with host-identical hostname-counter consumption
        and topology narrowing (scheduler.go:478-556 + nodeclaim.go:114-163).
        No memoized ERROR short-circuit: the host re-runs the template loop
        (and consumes placeholder hostnames) on every retry, and hostname
        STRINGS are decision-relevant under sorted-domain iteration.
        SUCCESSFUL openings are memoized per shape group and replayed while
        the matched groups' count generations stand still — repeat openings
        (the dominant cost on anti-affinity-heavy solves, where claims
        saturate after a few pods) cost two dict hits and the placeholder
        draws instead of the full template loop."""
        memo = self._open_memo.get(gi)
        if memo is not None:
            toks, gens, outcomes = memo
            k = 0
            for tg in toks:
                if gens[k] != tg._gen:
                    break
                k += 1
            else:
                self._replay_open(pod, gi, outcomes)
                return None
        s, topo = self.s, self.topology
        gp = self.g_ports[gi]
        # (nodepool, error): the pool attribution feeds the explanation
        # funnel (observability/explain.py); the joined message is unchanged
        errs: list[tuple[str, Exception]] = []
        outcomes: list = []
        memo_ok = True
        # gens are captured at ENTRY: the memo is valid only while the
        # counts the evaluation below actually SAW stand still. The
        # opening's own records then invalidate it for the next open —
        # exactly when the next-domain choice could differ.
        memo_toks = self._open_memo_tokens(gi)
        entry_gens = (
            [tg._gen for tg in memo_toks] if memo_toks is not None else None
        )
        for ti, nct in enumerate(s.nodeclaim_templates):
            remaining = self.remaining_resources.get(nct.nodepool_name)
            limits_mask = None
            if remaining:
                # active limits shift per open; the opening memo only covers
                # limitless pools
                memo_ok = False
                limits_mask = self._limits_mask(nct.nodepool_name, remaining)
                if not (limits_mask & self.tmpl_mask[ti]).any():
                    errs.append(
                        (
                            nct.nodepool_name,
                            ValueError(
                                f"all available instance types exceed limits "
                                f"for nodepool {nct.nodepool_name!r}"
                            ),
                        )
                    )
                    continue
            # the host constructs the NodeClaim here, consuming a hostname
            # placeholder even when can_add then fails
            hostname = f"hostname-placeholder-{next(ncmod._hostname_counter):04d}"
            outcomes.append(None)  # assume draw-and-fail; success overwrites
            tol = self.tg_tol.get((ti, gi))
            if tol is None:
                tol = Taints(nct.spec.taints).tolerates_pod(pod) is None
                self.tg_tol[(ti, gi)] = tol
            if not tol:
                errs.append(
                    (
                        nct.nodepool_name,
                        ValueError(
                            str(Taints(nct.spec.taints).tolerates_pod(pod))
                        ),
                    )
                )
                continue
            if gp:
                conflict = s.daemon_hostports[nct].conflicts(pod, gp)
                if conflict is not None:
                    errs.append(
                        (
                            nct.nodepool_name,
                            ValueError(f"checking host port usage, {conflict}"),
                        )
                    )
                    continue
            if g.has_hostname:
                # the host's compat gate runs with the claim's placeholder
                # hostname row included (nodeclaim.go:285-291) — reproduce
                # its exact error text, placeholder string and all
                claim_reqs = Requirements(*nct.requirements.values())
                claim_reqs.add(
                    Requirement(wk.LABEL_HOSTNAME, Operator.IN, [hostname])
                )
                cerr = claim_reqs.compatible(
                    g.reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
                )
                if cerr is not None:
                    errs.append(
                        (
                            nct.nodepool_name,
                            ValueError(f"incompatible requirements, {cerr}"),
                        )
                    )
                    continue
            tg = self._tg(ti, gi)
            if tg is None:
                errs.append(
                    (
                        nct.nodepool_name,
                        ValueError(
                            "incompatible requirements, "
                            + str(
                                nct.requirements.compatible(
                                    g.reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
                                )
                            )
                        ),
                    )
                )
                continue
            joint_tg, _rows = tg
            joint = Requirements(*joint_tg.values())
            joint.add(Requirement(wk.LABEL_HOSTNAME, Operator.IN, [hostname]))
            try:
                topo_reqs = topo.add_requirements(
                    pod,
                    nct.spec.taints,
                    g.strict_reqs,
                    joint,
                    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
                )
            except ValueError as e:
                errs.append((nct.nodepool_name, e))
                continue
            topo_err = joint.compatible(topo_reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS)
            if topo_err is not None:
                errs.append((nct.nodepool_name, ValueError(topo_err)))
                continue
            joint.add(*topo_reqs.values())
            final_rows = self._rows_sans_hostname(joint)
            compat_v, offer_v = self._joint_masks(final_rows, joint)
            base = self.tmpl_mask[ti]
            if limits_mask is not None:
                base = base & limits_mask
            candidate = base & compat_v & offer_v
            cand_u = np.unique(self.uid_of_type[candidate])
            rem0 = self.uniq_alloc[cand_u] - (self.usage0_f[ti] + g.req_f)
            fitrows = (rem0 >= -_EPS).all(axis=1)
            if not fitrows.any():
                errs.append(
                    (
                        nct.nodepool_name,
                        self._filter_error(base, compat_v, offer_v, ti, g),
                    )
                )
                continue
            u_ids = cand_u[fitrows]
            final = self._final_types(candidate, u_ids)
            min_specs, min_relaxed = self.tmpl_min[ti], False
            if self.min_active and self.tmpl_min[ti]:
                min_specs, min_relaxed, msg = self._min_open(ti, final)
                if msg is not None:
                    err = self._filter_error(base, compat_v, offer_v, ti, g)
                    err.min_values_incompatible = msg
                    errs.append((nct.nodepool_name, err))
                    continue
            if self.strict_res:
                try:
                    self._pending_reserved = self._reserved_eval(
                        hostname, joint, final
                    )
                except ncmod.ReservedOfferingError as e:
                    # earliest-index-wins: the reserved error preempts later
                    # templates AND any collected errors (scheduler.go:574,
                    # 486-490 tail)
                    return e
            elif self.res_active:
                self._pending_reserved = None
            fam = self._intern_fam(final_rows, self._sans_hostname(joint))
            rem0_fit = rem0[fitrows]
            self._open_claim(
                ti, fam, pod, gi, candidate, u_ids, rem0_fit.copy(),
                hostname=hostname, min_specs=min_specs, min_relaxed=min_relaxed,
                pareto=self._pareto_for(rem0_fit) if self._defer_ok else None,
            )
            if self._any_ports:
                hp = s.daemon_hostports[nct].copy()
                if gp:
                    hp.add(pod, gp)
                self._claim_hp[len(self.claims) - 1] = hp
            self._apply_record_plan(gi, self.claims[-1])
            self._subtract_max(nct, final)
            if memo_ok and memo_toks is not None:
                outcomes[-1] = (
                    ti, fam, candidate, u_ids, rem0_fit,
                    min_specs, min_relaxed,
                )
                self._open_memo[gi] = (memo_toks, entry_gens, outcomes)
            return None
        from karpenter_tpu.observability import explain as explmod

        rec = explmod.recorder()
        if rec.enabled and errs:
            # stage the per-nodepool funnel, exactly as the host scheduler
            # does (scheduler.py _add_to_new_node_claim) — the solve barrier
            # commits it only if the pod stays failed
            rec.note_funnel(pod.metadata.uid, explmod.funnel_from(errs))
        if not errs:
            errs.append(("", ValueError("no nodepool can host the pod")))
        return (
            errs[0][1]
            if len(errs) == 1
            else ValueError("; ".join(str(e) for _, e in errs))
        )

    def _restore_relaxed(self, pod: Pod) -> None:
        """Final-failure tail of a relax chain: restore the ORIGINAL pod's
        topology ownership and cached data (scheduler.go:363-367)."""
        self.topology.update(pod)
        self.s.update_cached_pod_data(pod)
        self._relax_restore.pop(pod.metadata.uid, None)

    # -- attempt / relax loop ------------------------------------------------

    def _try_once(self, pod: Pod, gi: int) -> Optional[Exception]:
        """One host `_add` pass: existing nodes → in-flight claims → new
        claim (scheduler.go:436-449)."""
        g = self.groups[gi]
        volatile = self.g_volatile[gi]
        if self.nodes:
            if volatile:
                placed = self._try_nodes_topo(pod, g, gi)
            else:
                placed = self._try_nodes(pod, g, gi)
                if placed and self._needs_record(gi):
                    nd = self._joined_node
                    self.topology.record(pod, nd.en.cached_taints, nd.reqs)
            if placed:
                return None
        if volatile:
            placed = self._try_claims_topo(pod, g, gi)
        else:
            placed = self._try_claims(pod, g, gi)
            if placed and self._needs_record(gi):
                self._apply_record_plan(gi, self._joined)
        if placed:
            return None
        if not self.s.nodeclaim_templates:
            return ValueError(
                "nodepool requirements filtered out all available instance types"
            )
        return self._new_claim_topo(pod, g, gi)

    def _attempt(self, pod: Pod, gi: int) -> Optional[Exception]:
        """Host `_try_schedule`: attempt, then relax one preference at a time
        on failure, topology.update + pod-data refresh between steps
        (scheduler.go:351-371). Final failure restores the original pod's
        ownership and cached data (scheduler.go:363-367 error tail)."""
        s = self.s
        p, pgi = pod, gi
        relaxed_any = False
        while True:
            err = self._try_once(p, pgi)
            if err is None:
                return None
            if isinstance(err, ncmod.ReservedOfferingError):
                # a new-claim reserved error preempts relaxation —
                # _try_schedule re-raises it (scheduler.go:374-375)
                if relaxed_any:
                    self._restore_relaxed(pod)
                return err
            if not self.g_relaxable[pgi]:
                if relaxed_any:
                    self._restore_relaxed(pod)
                return err
            rc = copy.deepcopy(p) if p is pod else p
            if not s.preferences.relax(rc):
                if relaxed_any:
                    self._restore_relaxed(pod)
                return err
            relaxed_any = True
            self._relax_restore.setdefault(pod.metadata.uid, pod)
            self.topology.update(rc)
            self._maybe_refresh_groups()
            s.update_cached_pod_data(rc)
            ngi = self._ensure_group(rc)
            if ngi is None:
                raise _Fallback("relaxed shape ineligible")
            p, pgi = rc, ngi

    # -- main loop -----------------------------------------------------------

    def run(self, timeout: Optional[float]) -> None:
        gi_arr = self._group_pods()
        if gi_arr is None:
            raise _IneligibleShape("ineligible pod shape")
        self._prepare_templates()
        # deferred row-pruning: legal whenever no per-join row reads exist —
        # minValues gates and reserved bookkeeping both read u_ids per join
        self._defer_ok = not (self.min_active or self.res_active)
        order = self._order(gi_arr)
        self._snapshot_topology()
        qpods = [(self.pods[i], int(gi_arr[i])) for i in order]
        head = 0
        last_len: dict[str, int] = {}
        pod_errors = self.pod_errors
        start = time.perf_counter()
        check = 0
        # fast-lane conditions hoisted out of the loop: with no existing
        # nodes and a non-relaxable shape, one attempt is exactly
        # claim-scan → new-claim (no _attempt/_try_once dispatch)
        relaxable = self.g_relaxable
        volatile = self.g_volatile
        has_nodes = bool(self.nodes)
        has_templates = bool(self.s.nodeclaim_templates)
        groups = self.groups
        while head < len(qpods):
            pod, gi = qpods[head]
            if last_len and last_len.get(pod.metadata.uid) == len(qpods) - head:
                break
            check += 1
            if timeout is not None and not (check & 0x3F):
                if time.perf_counter() - start > timeout:
                    self.timed_out = True
                    for p, _ in qpods[head:]:
                        pod_errors.setdefault(
                            p, TimeoutError("scheduling simulation timed out")
                        )
                    return
            head += 1
            if not has_nodes and not relaxable[gi] and has_templates and volatile[gi]:
                if self._try_claims_topo(pod, groups[gi], gi):
                    err = None
                else:
                    err = self._new_claim_topo(pod, groups[gi], gi)
            else:
                err = self._attempt(pod, gi)
            if err is None:
                if pod_errors:
                    pod_errors.pop(pod, None)
            else:
                pod_errors[pod] = err
                qpods.append((pod, gi))
                last_len[pod.metadata.uid] = len(qpods) - head

    def emit(self):
        super().emit()
        _TOPO_SOLVES_CTR.inc()
