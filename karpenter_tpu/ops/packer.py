"""Device-batched group solver: the TPU fast path for large pod batches.

The reference scales its FFD solver with goroutine fan-out over pods
(scheduler.go:677-699); the TPU equivalent (SURVEY.md §2, §7) reshapes the
work as array programs:

1. Pods are deduplicated into groups by (requirement rows, quantized
   requests) — a 50k-pod batch typically collapses to a few hundred shapes.
2. One fused device program computes the full feasibility cube
   compat ∧ fits ∧ offering over [G groups × I instance types] (the
   membership matmuls ride the MXU), picks each group's cheapest feasible
   type, and computes per-group node counts via integer packing math.
3. The pod axis shards over a `jax.sharding.Mesh` (shard_map) for
   multi-chip: groups are data-parallel; the catalog is replicated so all
   reductions stay local — no cross-chip collectives needed until the final
   scalar sums (psum).

Resources are quantized to int32 milli-units (requests rounded up,
capacities down) so packing decisions can only be stricter than the float64
host oracle, never looser (ops/feasibility.quantize_resources).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map

    _SHARD_MAP_UNCHECKED = {"check_vma": False}
except ImportError:  # jax < 0.6 keeps shard_map under jax.experimental
    from jax.experimental.shard_map import shard_map

    _SHARD_MAP_UNCHECKED = {"check_rep": False}
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.ops import encoding as enc
from karpenter_tpu.ops import feasibility as feas
from karpenter_tpu.tracing import kernel as ktime
from karpenter_tpu.ops.catalog import CatalogEngine
from karpenter_tpu.scheduling.requirements import Requirements

INF_PRICE = jnp.float32(3.4e38)


@dataclass
class GroupedPods:
    """Pod batch collapsed to distinct shapes."""

    membership: np.ndarray  # [G, R] bool — requirement rows per group
    requests_q: np.ndarray  # [G, D] int64 milli-units (rounded up)
    key_present: np.ndarray  # [G, K] bool
    counts: np.ndarray  # [G] int32 — pods per group
    group_of_pod: np.ndarray  # [P] int32


def _solve_block(
    group_bools,  # [G, R+K] bool — membership | key_present packed
    group_ints,  # [G, D+1] int32 — requests_q | counts packed
    req_compat,  # [R, I] bool
    offer_compat,  # [R, O] bool
    custom_need,  # [O, K] bool
    available,  # [O] bool
    owner_onehot,  # [O, I] bool
    alloc_q,  # [I, D] int32
    price,  # [I] float32 — cheapest available offering per type
):
    """The fused per-shard solve: feasibility cube → cheapest-type argmin →
    integer packing. Pure array math; runs under jit/shard_map. Group inputs
    arrive packed (2 host->device transfers instead of 4 — the tunneled-TPU
    round trip dominates at this problem size) and split on static shapes."""
    R = req_compat.shape[0]
    D = alloc_q.shape[1]
    membership = group_bools[:, :R]
    key_present = group_bools[:, R:]
    requests_q = group_ints[:, :D]
    counts = group_ints[:, D]
    compat = feas.membership_all(membership, req_compat)  # [G, I]
    fits = jnp.all(requests_q[:, None, :] <= alloc_q[None, :, :], axis=-1)  # [G, I]
    has_offering = feas.offering_reduce(
        membership, offer_compat, custom_need, key_present, available, owner_onehot
    )
    feasible = compat & fits & has_offering  # [G, I]

    score = jnp.where(feasible, price[None, :], INF_PRICE)
    choice = jnp.argmin(score, axis=-1)  # [G] cheapest feasible type
    feasible_any = jnp.any(feasible, axis=-1)

    # pods-per-node for the chosen type: min over resource dims of
    # floor(alloc / request); request==0 dims don't constrain
    chosen_alloc = alloc_q[choice]  # [G, D]
    per_dim = jnp.where(
        requests_q > 0,
        chosen_alloc // jnp.maximum(requests_q, 1),
        jnp.iinfo(jnp.int32).max,
    )
    pods_per_node = jnp.maximum(jnp.min(per_dim, axis=-1), 0)  # [G]
    nodes = jnp.where(
        feasible_any & (pods_per_node > 0),
        -(-counts // jnp.maximum(pods_per_node, 1)),  # ceil div
        0,
    )
    unschedulable = jnp.where(
        feasible_any & (pods_per_node > 0), 0, counts
    )
    # Single packed output: one device->host transfer instead of four — the
    # tunneled-TPU round trip (~100ms) dominates at this problem size.
    return jnp.stack(
        [
            choice.astype(jnp.int32),
            feasible_any.astype(jnp.int32),
            nodes.astype(jnp.int32),
            unschedulable.astype(jnp.int32),
        ],
        axis=1,
    )


solve_block_jit = jax.jit(_solve_block)

# One jitted shard_map per (mesh, axis), shared by every GroupSolver on the
# mesh AND by the AOT compiler's warm-start walk — the walk must pre-compile
# through the SAME wrapper the serving path dispatches, or the jit caches
# (and the compile accounting) would split.
_SHARDED_SOLVE_FNS: dict[tuple, object] = {}


def sharded_solve_block(mesh: Mesh, axis: str = "pods"):
    """jit(shard_map(_solve_block)) for `mesh`: groups data-parallel over
    `axis`, the full catalog replicated per chip, the packed result
    all-gathered only at emit (out_specs=P(axis)) — no collectives inside
    the solve."""
    fn = _SHARDED_SOLVE_FNS.get((mesh, axis))
    if fn is None:
        n_catalog_args = 7
        in_specs = (P(axis), P(axis)) + tuple(P() for _ in range(n_catalog_args))
        fn = jax.jit(
            shard_map(
                _solve_block, mesh=mesh, in_specs=in_specs,
                out_specs=P(axis), **_SHARD_MAP_UNCHECKED,
            )
        )
        _SHARDED_SOLVE_FNS[(mesh, axis)] = fn
    return fn


# the AOT table/cache scope of a mesh — defined beside the sharded cube
# (ops/feasibility.mesh_scope) so ops/catalog shares it without importing
# this module
mesh_scope = feas.mesh_scope


def _pack_groups(grouped: "GroupedPods") -> tuple[np.ndarray, np.ndarray]:
    group_bools = np.concatenate([grouped.membership, grouped.key_present], axis=1)
    group_ints = np.concatenate(
        [grouped.requests_q.astype(np.int32), grouped.counts[:, None]], axis=1
    )
    return group_bools, group_ints


class GroupSolver:
    """Host wrapper: engine matrices + per-type prices, device solve."""

    def __init__(self, engine: CatalogEngine, mesh: Optional[Mesh] = None):
        self.engine = engine
        # an explicit mesh wins; otherwise inherit the engine's — a solver
        # built on a mesh-sharded engine serves mesh-sharded solves without
        # every call site knowing about meshes
        self.mesh = mesh if mesh is not None else engine.mesh
        # cheapest available offering price per instance type
        price = np.full(engine.num_instances, np.inf, dtype=np.float32)
        for o_idx, owner in enumerate(engine.offering_owner):
            if engine.offering_available[o_idx]:
                price[owner] = min(price[owner], engine.offering_price[o_idx])
        self.price = price
        scales = feas.resource_scales(engine.resource_dims)
        self.alloc_q = feas.quantize_resources(
            engine.allocatable, ceil=False, scales=scales
        ).astype(np.int32)
        self._dev_args = None
        self._dev_rows = -1
        self._mesh_args = None
        self._mesh_args_key = None

    def _catalog_args(self):
        """Device-resident catalog matrices, uploaded once per row-set."""
        e = self.engine
        e._ensure_rows()
        if self._dev_args is not None and self._dev_rows == e._computed_rows:
            return self._dev_args
        self._dev_args = (
            jnp.asarray(e._req_compat if e._computed_rows else np.zeros((1, e.num_instances), bool)),
            jnp.asarray(e._offer_compat if e._computed_rows else np.zeros((1, e.num_offerings), bool)),
            jnp.asarray(e.offering_custom_need),
            jnp.asarray(e.offering_available),
            jnp.asarray(e._owner_onehot),
            jnp.asarray(self.alloc_q),
            jnp.asarray(self.price),
        )
        self._dev_rows = e._computed_rows
        return self._dev_args

    def _mesh_catalog_args(self, mesh: Mesh) -> tuple:
        """Mesh-replicated catalog matrices, shipped to every chip once per
        (mesh, row-set) — the _catalog_args analogue for sharded solves.
        Replicates from the HOST copies: bouncing the cached single-device
        jnp arrays through np.asarray would round-trip the whole catalog
        device→host→mesh on every solve."""
        e = self.engine
        e._ensure_rows()
        key = (mesh, e._computed_rows)
        if self._mesh_args_key == key:
            return self._mesh_args
        rep = NamedSharding(mesh, P())
        host = (
            e._req_compat
            if e._computed_rows
            else np.zeros((1, e.num_instances), bool),
            e._offer_compat
            if e._computed_rows
            else np.zeros((1, e.num_offerings), bool),
            e.offering_custom_need,
            e.offering_available,
            e._owner_onehot,
            self.alloc_q,
            self.price,
        )
        self._mesh_args = tuple(
            jax.device_put(np.asarray(a), rep) for a in host
        )
        self._mesh_args_key = key
        return self._mesh_args

    def solve(self, grouped: GroupedPods):
        """Fused solve; returns host arrays
        (choice, feasible, nodes-per-group, unschedulable-per-group).
        With a mesh attached (GroupSolver(mesh=) or the engine's), the
        group axis shards across its devices via solve_sharded — same
        decisions, computed in parallel. Dispatch goes through the kernel
        timer so the solve span can split wall time into compile vs execute
        (tracing/kernel.py). With an AOT ladder attached to the engine, the
        group axis pads up to its bucket (zero rows: counts 0 → nodes 0,
        sliced off) so the dispatch hits a warm-started executable."""
        if self.mesh is not None:
            return self.solve_sharded(grouped, self.mesh)
        args = self._catalog_args()
        group_bools, group_ints = _pack_groups(grouped)
        G = group_bools.shape[0]
        ladder = getattr(self.engine, "aot_ladder", None)
        if ladder is not None:
            from karpenter_tpu.aot import runtime as aotrt

            bucket = ladder.bucket_for("packer.solve_block", (G,))
            if bucket is None:
                # pow2-normalized: bounded warning/event cardinality
                aotrt.note_off_ladder(
                    "packer.solve_block",
                    str(1 << max(0, (G - 1).bit_length())),
                )
            elif bucket[0] > G:
                pad = bucket[0] - G
                group_bools = np.pad(group_bools, ((0, pad), (0, 0)))
                group_ints = np.pad(group_ints, ((0, pad), (0, 0)))
        out = np.asarray(
            ktime.dispatch(
                solve_block_jit,
                group_bools,
                group_ints,
                *args,
                kernel="packer.solve_block",
            )
        )[:G]
        return out[:, 0], out[:, 1].astype(bool), out[:, 2], out[:, 3]

    def solve_sharded(self, grouped: GroupedPods, mesh: Mesh, axis: str = "pods"):
        """Multi-chip solve: groups sharded over `axis`, catalog replicated
        (the §7 DP-style layout — collectives only for the final sums).

        The group axis pads to a mesh-size-INVARIANT global shape: the AOT
        ladder's sharded rung when one fits (divisible by the mesh size, so
        every shard gets an equal slab), else pow2 aligned to
        lcm(n, MESH_ALIGN). Padding rows carry counts 0 — they pack to 0
        nodes / 0 unschedulable on whatever shard they land on (an entirely-
        padding shard computes only zeros) and are sliced off before any
        claim is emitted."""
        from karpenter_tpu.aot import ladder as ladder_mod

        n = mesh.shape[axis]
        G = grouped.membership.shape[0]
        group_bools, group_ints = _pack_groups(grouped)

        align = ladder_mod.mesh_multiple(n)
        G2 = max(1 << max(0, (G - 1).bit_length()), align)
        G2 = -(-G2 // align) * align
        ladder = getattr(self.engine, "aot_ladder", None)
        scope = mesh_scope(mesh)
        if ladder is not None:
            bucket = ladder.bucket_for(
                "packer.solve_block_sharded", (G,), multiple_of=n
            )
            if bucket is None:
                # off-ladder: this global shape jit-compiles a sharded
                # executable the warm start never prepaid; the mesh rides
                # the shape label so the event names the layout that missed
                from karpenter_tpu.aot import runtime as aotrt

                aotrt.note_off_ladder(
                    "packer.solve_block_sharded", str(G2), mesh=scope
                )
            else:
                G2 = bucket[0]
        if G2 > G:
            pad = G2 - G
            group_bools = np.pad(group_bools, ((0, pad), (0, 0)))
            group_ints = np.pad(group_ints, ((0, pad), (0, 0)))

        fn = sharded_solve_block(mesh, axis)
        sharding = NamedSharding(mesh, P(axis))
        dev_args = [
            jax.device_put(group_bools, sharding),
            jax.device_put(group_ints, sharding),
        ] + list(self._mesh_catalog_args(mesh))
        out = np.asarray(
            ktime.dispatch(
                fn, *dev_args,
                kernel="packer.solve_block_sharded", aot_scope=scope,
            )
        )
        return (
            out[:G, 0],
            out[:G, 1].astype(bool),
            out[:G, 2],
            out[:G, 3],
        )


def scatter_add_counts(
    counts: np.ndarray, idx: Sequence[int], amount: int = 1
) -> np.ndarray:
    """Unbuffered scatter-add of `amount` into `counts` at `idx` (duplicate
    indices accumulate, matching `jnp.ndarray.at[].add` semantics), growing
    the vector geometrically when an index lands past the end. This is the
    update primitive behind the topology count tensors (ops/topo_counts.py):
    one placement batch scatters its (group, domain) increments in a single
    call instead of a per-domain dict walk."""
    idx = np.asarray(idx, dtype=np.int64)
    if idx.size == 0:
        return counts
    hi = int(idx.max())
    if hi >= counts.shape[0]:
        grown = np.zeros(max(hi + 1, counts.shape[0] * 2), dtype=counts.dtype)
        grown[: counts.shape[0]] = counts
        counts = grown
    np.add.at(counts, idx, amount)
    return counts


def merge_shard_group_counts(
    shard_group_ids: Sequence[np.ndarray],
    num_groups: int,
    shard_amounts: Optional[Sequence[np.ndarray]] = None,
) -> np.ndarray:
    """Segment-reduce per-shard group-membership streams into ONE global
    [num_groups] count vector — the claim-emission merge for a pod-axis-
    sharded encode, where one group's pods may land on several shards and
    each shard only knows its local tally. Ids past num_groups are padding
    rows (the mesh-alignment remainder) and are MASKED OUT, never counted.
    With `shard_amounts`, entry j of shard s contributes amounts[s][j]
    instead of 1 (pre-reduced per-shard count tensors merge the same way).
    Semantics match np.add.at over the concatenated streams — duplicates
    accumulate, exactly like scatter_add_counts and the host dict walk.
    NOTE: the shipped encode (encode_pods_for_packer) groups on the host
    before sharding, so group counts arrive whole; this is the merge
    primitive for encodes that split the raw pod stream across shards
    (spec'd against the concatenated-scatter oracle in tests/test_mesh.py)."""
    out = np.zeros(num_groups, dtype=np.int64)
    for s, ids in enumerate(shard_group_ids):
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        amounts = (
            np.ones(ids.shape[0], dtype=np.int64)
            if shard_amounts is None
            else np.asarray(shard_amounts[s], dtype=np.int64).reshape(-1)
        )
        keep = (ids >= 0) & (ids < num_groups)
        np.add.at(out, ids[keep], amounts[keep])
    return out


def encode_pods_for_packer(
    engine: CatalogEngine, pods_requirements: Sequence[Requirements], requests: np.ndarray
) -> GroupedPods:
    """Requirements → engine rows → groups (the host-side encode step).
    Requirements objects repeated by identity (one object per workload
    shape) encode once."""
    shape_of: dict[int, int] = {}
    distinct: list[Requirements] = []
    shape_ids = np.empty(len(pods_requirements), dtype=np.int64)
    for p, reqs in enumerate(pods_requirements):
        sid = shape_of.get(id(reqs))
        if sid is None:
            sid = len(distinct)
            shape_of[id(reqs)] = sid
            distinct.append(reqs)
        shape_ids[p] = sid
    distinct_rows = [engine.rows_for(reqs) for reqs in distinct]
    kp_distinct = engine.key_presence(distinct)
    engine._ensure_rows()

    # Vectorized grouping: unique over (shape id, quantized request row).
    scales = feas.resource_scales(engine.resource_dims)
    requests_q = feas.quantize_resources(requests, ceil=True, scales=scales)
    combined = np.column_stack([shape_ids, requests_q])
    uniq, inverse, counts = np.unique(
        combined, axis=0, return_inverse=True, return_counts=True
    )
    G = uniq.shape[0]
    R = max(1, engine.num_rows)
    membership = np.zeros((G, R), dtype=bool)
    for g in range(G):
        for rid in distinct_rows[int(uniq[g, 0])]:
            membership[g, rid] = True
    return GroupedPods(
        membership=membership,
        requests_q=uniq[:, 1:],
        key_present=kp_distinct[uniq[:, 0].astype(np.int64)],
        counts=counts.astype(np.int32),
        group_of_pod=inverse.astype(np.int32),
    )
