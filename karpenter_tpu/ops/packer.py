"""Device-batched group solver: the TPU fast path for large pod batches.

The reference scales its FFD solver with goroutine fan-out over pods
(scheduler.go:677-699); the TPU equivalent (SURVEY.md §2, §7) reshapes the
work as array programs:

1. Pods are deduplicated into groups by (requirement rows, quantized
   requests) — a 50k-pod batch typically collapses to a few hundred shapes.
2. One fused device program computes the full feasibility cube
   compat ∧ fits ∧ offering over [G groups × I instance types] (the
   membership matmuls ride the MXU), picks each group's cheapest feasible
   type, and computes per-group node counts via integer packing math.
3. The pod axis shards over a `jax.sharding.Mesh` (shard_map) for
   multi-chip: groups are data-parallel; the catalog is replicated so all
   reductions stay local — no cross-chip collectives needed until the final
   scalar sums (psum).

Resources are quantized to int32 milli-units (requests rounded up,
capacities down) so packing decisions can only be stricter than the float64
host oracle, never looser (ops/feasibility.quantize_resources).
"""

from __future__ import annotations

import functools
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
try:
    from jax import shard_map

    _SHARD_MAP_UNCHECKED = {"check_vma": False}
except ImportError:  # jax < 0.6 keeps shard_map under jax.experimental
    from jax.experimental.shard_map import shard_map

    _SHARD_MAP_UNCHECKED = {"check_rep": False}
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.ops import encoding as enc
from karpenter_tpu.ops import feasibility as feas
from karpenter_tpu.tracing import kernel as ktime
from karpenter_tpu.ops.catalog import CatalogEngine
from karpenter_tpu.scheduling.requirements import Requirements

INF_PRICE = jnp.float32(3.4e38)


@dataclass
class GroupedPods:
    """Pod batch collapsed to distinct shapes."""

    membership: np.ndarray  # [G, R] bool — requirement rows per group
    requests_q: np.ndarray  # [G, D] int64 milli-units (rounded up)
    key_present: np.ndarray  # [G, K] bool
    counts: np.ndarray  # [G] int32 — pods per group
    group_of_pod: np.ndarray  # [P] int32


def _solve_parts(
    group_bools,  # [G, R+K] bool — membership | key_present packed
    group_ints,  # [G, D+1] int32 — requests_q | counts packed
    req_compat,  # [R, I] bool
    offer_compat,  # [R, O] bool
    custom_need,  # [O, K] bool
    available,  # [O] bool
    owner_onehot,  # [O, I] bool
    alloc_q,  # [I, D] int32
    price,  # [I] float32 — cheapest available offering per type
):
    """The count-INDEPENDENT solve math: feasibility cube → cheapest-type
    argmin → pods-per-node. Shared verbatim by the full solve (`_solve_block`)
    and the delta core (`_solve_block_core`), so the incremental path is
    bit-identical by construction — same trace, different finalize."""
    R = req_compat.shape[0]
    D = alloc_q.shape[1]
    membership = group_bools[:, :R]
    key_present = group_bools[:, R:]
    requests_q = group_ints[:, :D]
    counts = group_ints[:, D]
    compat = feas.membership_all(membership, req_compat)  # [G, I]
    fits = jnp.all(requests_q[:, None, :] <= alloc_q[None, :, :], axis=-1)  # [G, I]
    has_offering = feas.offering_reduce(
        membership, offer_compat, custom_need, key_present, available, owner_onehot
    )
    feasible = compat & fits & has_offering  # [G, I]

    score = jnp.where(feasible, price[None, :], INF_PRICE)
    choice = jnp.argmin(score, axis=-1)  # [G] cheapest feasible type
    feasible_any = jnp.any(feasible, axis=-1)

    # pods-per-node for the chosen type: min over resource dims of
    # floor(alloc / request); request==0 dims don't constrain
    chosen_alloc = alloc_q[choice]  # [G, D]
    per_dim = jnp.where(
        requests_q > 0,
        chosen_alloc // jnp.maximum(requests_q, 1),
        jnp.iinfo(jnp.int32).max,
    )
    pods_per_node = jnp.maximum(jnp.min(per_dim, axis=-1), 0)  # [G]
    return choice, feasible_any, pods_per_node, counts


def _count_finalize(choice, feasible_any, pods_per_node, counts):
    """Fold this pass's group counts over the count-independent core:
    nodes via ceil division, unschedulable as the infeasible remainder."""
    nodes = jnp.where(
        feasible_any & (pods_per_node > 0),
        -(-counts // jnp.maximum(pods_per_node, 1)),  # ceil div
        0,
    )
    unschedulable = jnp.where(
        feasible_any & (pods_per_node > 0), 0, counts
    )
    # Single packed output: one device->host transfer instead of four — the
    # tunneled-TPU round trip (~100ms) dominates at this problem size.
    return jnp.stack(
        [
            choice.astype(jnp.int32),
            feasible_any.astype(jnp.int32),
            nodes.astype(jnp.int32),
            unschedulable.astype(jnp.int32),
        ],
        axis=1,
    )


def _solve_block(
    group_bools, group_ints, req_compat, offer_compat, custom_need,
    available, owner_onehot, alloc_q, price,
):
    """The fused per-shard solve: feasibility cube → cheapest-type argmin →
    integer packing. Pure array math; runs under jit/shard_map. Group inputs
    arrive packed (2 host->device transfers instead of 4 — the tunneled-TPU
    round trip dominates at this problem size) and split on static shapes."""
    choice, feasible_any, pods_per_node, counts = _solve_parts(
        group_bools, group_ints, req_compat, offer_compat, custom_need,
        available, owner_onehot, alloc_q, price,
    )
    return _count_finalize(choice, feasible_any, pods_per_node, counts)


solve_block_jit = jax.jit(_solve_block)


# -- delta kernels: frontier core solve + donated scatter + finalize ----------
#
# The incremental group solve (ops/delta.py) keeps the count-INDEPENDENT
# core results (choice, feasible, pods-per-node) device-resident keyed by
# group content fingerprint. A churn pass solves only the perturbed frontier
# through `_solve_block_core`, scatters the fresh rows into the resident
# matrix with the RESIDENCY BUFFER DONATED (XLA writes in place — the
# steady-state cost of holding the matrix is zero copies), then finalizes
# nodes/unschedulable against this pass's counts.


def _solve_block_core(
    group_bools, group_ints, req_compat, offer_compat, custom_need,
    available, owner_onehot, alloc_q, price,
):
    """[Gf, 3] int32 core rows (choice, feasible, pods-per-node) for the
    perturbed frontier — `_solve_parts` verbatim, counts ignored."""
    choice, feasible_any, pods_per_node, _ = _solve_parts(
        group_bools, group_ints, req_compat, offer_compat, custom_need,
        available, owner_onehot, alloc_q, price,
    )
    return jnp.stack(
        [
            choice.astype(jnp.int32),
            feasible_any.astype(jnp.int32),
            pods_per_node.astype(jnp.int32),
        ],
        axis=1,
    )


solve_block_core_jit = jax.jit(_solve_block_core)


def _delta_scatter_rows(core, slots, rows):
    """Scatter freshly-solved frontier rows into the resident core matrix.
    `core` is DONATED — the update happens in place on device. Padding
    entries duplicate the last slot with the same row values: same-value
    scatter collisions are well-defined no-ops."""
    return core.at[slots].set(rows)


delta_scatter_rows = jax.jit(_delta_scatter_rows, donate_argnums=(0,))


def _delta_finalize(core, order, counts):
    """Gather this pass's group order from the resident core and fold in
    its counts — the exact `_count_finalize` math, so a delta pass's packed
    output is bit-identical to the full solve's. `core` is NOT donated (it
    must survive for the next pass)."""
    rows = core[order]
    choice = rows[:, 0]
    feasible_any = rows[:, 1].astype(bool)
    pods_per_node = rows[:, 2]
    return _count_finalize(choice, feasible_any, pods_per_node, counts)


delta_finalize = jax.jit(_delta_finalize)

# One jitted shard_map per (mesh, axis), shared by every GroupSolver on the
# mesh AND by the AOT compiler's warm-start walk — the walk must pre-compile
# through the SAME wrapper the serving path dispatches, or the jit caches
# (and the compile accounting) would split.
_SHARDED_SOLVE_FNS: dict[tuple, object] = {}


def sharded_solve_block(mesh: Mesh, axis: str = "pods"):
    """jit(shard_map(_solve_block)) for `mesh`: groups data-parallel over
    `axis`, the full catalog replicated per chip, the packed result
    all-gathered only at emit (out_specs=P(axis)) — no collectives inside
    the solve."""
    fn = _SHARDED_SOLVE_FNS.get((mesh, axis))
    if fn is None:
        n_catalog_args = 7
        in_specs = (P(axis), P(axis)) + tuple(P() for _ in range(n_catalog_args))
        fn = jax.jit(
            shard_map(
                _solve_block, mesh=mesh, in_specs=in_specs,
                out_specs=P(axis), **_SHARD_MAP_UNCHECKED,
            )
        )
        _SHARDED_SOLVE_FNS[(mesh, axis)] = fn
    return fn


# the AOT table/cache scope of a mesh — defined beside the sharded cube
# (ops/feasibility.mesh_scope) so ops/catalog shares it without importing
# this module
mesh_scope = feas.mesh_scope


def _pack_groups(grouped: "GroupedPods") -> tuple[np.ndarray, np.ndarray]:
    group_bools = np.concatenate([grouped.membership, grouped.key_present], axis=1)
    group_ints = np.concatenate(
        [grouped.requests_q.astype(np.int32), grouped.counts[:, None]], axis=1
    )
    return group_bools, group_ints


class GroupSolver:
    """Host wrapper: engine matrices + per-type prices, device solve."""

    def __init__(self, engine: CatalogEngine, mesh: Optional[Mesh] = None):
        self.engine = engine
        # an explicit mesh wins; otherwise inherit the engine's — a solver
        # built on a mesh-sharded engine serves mesh-sharded solves without
        # every call site knowing about meshes
        self.mesh = mesh if mesh is not None else engine.mesh
        # cheapest available offering price per instance type
        price = np.full(engine.num_instances, np.inf, dtype=np.float32)
        for o_idx, owner in enumerate(engine.offering_owner):
            if engine.offering_available[o_idx]:
                price[owner] = min(price[owner], engine.offering_price[o_idx])
        self.price = price
        scales = feas.resource_scales(engine.resource_dims)
        self.alloc_q = feas.quantize_resources(
            engine.allocatable, ceil=False, scales=scales
        ).astype(np.int32)
        self._dev_args = None
        self._dev_rows = -1
        self._mesh_args = None
        self._mesh_args_key = None

    def _catalog_args(self):
        """Device-resident catalog matrices, uploaded once per row-set."""
        e = self.engine
        e._ensure_rows()
        if self._dev_args is not None and self._dev_rows == e._computed_rows:
            return self._dev_args
        self._dev_args = (
            jnp.asarray(e._req_compat if e._computed_rows else np.zeros((1, e.num_instances), bool)),
            jnp.asarray(e._offer_compat if e._computed_rows else np.zeros((1, e.num_offerings), bool)),
            jnp.asarray(e.offering_custom_need),
            jnp.asarray(e.offering_available),
            jnp.asarray(e._owner_onehot),
            jnp.asarray(self.alloc_q),
            jnp.asarray(self.price),
        )
        self._dev_rows = e._computed_rows
        return self._dev_args

    def _mesh_catalog_args(self, mesh: Mesh) -> tuple:
        """Mesh-replicated catalog matrices, shipped to every chip once per
        (mesh, row-set) — the _catalog_args analogue for sharded solves.
        Replicates from the HOST copies: bouncing the cached single-device
        jnp arrays through np.asarray would round-trip the whole catalog
        device→host→mesh on every solve."""
        e = self.engine
        e._ensure_rows()
        key = (mesh, e._computed_rows)
        if self._mesh_args_key == key:
            return self._mesh_args
        rep = NamedSharding(mesh, P())
        host = (
            e._req_compat
            if e._computed_rows
            else np.zeros((1, e.num_instances), bool),
            e._offer_compat
            if e._computed_rows
            else np.zeros((1, e.num_offerings), bool),
            e.offering_custom_need,
            e.offering_available,
            e._owner_onehot,
            self.alloc_q,
            self.price,
        )
        self._mesh_args = tuple(
            jax.device_put(np.asarray(a), rep) for a in host
        )
        self._mesh_args_key = key
        return self._mesh_args

    def solve(self, grouped: GroupedPods):
        """Fused solve; returns host arrays
        (choice, feasible, nodes-per-group, unschedulable-per-group).
        With a mesh attached (GroupSolver(mesh=) or the engine's), the
        group axis shards across its devices via solve_sharded — same
        decisions, computed in parallel. Dispatch goes through the kernel
        timer so the solve span can split wall time into compile vs execute
        (tracing/kernel.py). With an AOT ladder attached to the engine, the
        group axis pads up to its bucket (zero rows: counts 0 → nodes 0,
        sliced off) so the dispatch hits a warm-started executable.

        With delta solves on (--delta-solve / KARPENTER_TPU_DELTA), the
        single-device path routes through the per-solver residency
        (ops/delta.py): only the perturbed group frontier is re-solved and
        scatter-applied into the device-resident core matrix."""
        if self.mesh is not None:
            return self.solve_sharded(grouped, self.mesh)
        from karpenter_tpu.ops import delta as delta_mod

        if delta_mod.delta_enabled():
            return delta_mod.group_residency(self).solve(self, grouped)
        return self._solve_full(grouped)

    def _solve_full(self, grouped: GroupedPods):
        """The from-scratch single-device solve — the delta path's seed,
        fallback, and periodic self-check oracle."""
        args = self._catalog_args()
        group_bools, group_ints = _pack_groups(grouped)
        G = group_bools.shape[0]
        ladder = getattr(self.engine, "aot_ladder", None)
        if ladder is not None:
            from karpenter_tpu.aot import runtime as aotrt

            bucket = ladder.bucket_for("packer.solve_block", (G,))
            if bucket is None:
                # pow2-normalized: bounded warning/event cardinality
                aotrt.note_off_ladder(
                    "packer.solve_block",
                    str(1 << max(0, (G - 1).bit_length())),
                )
            elif bucket[0] > G:
                pad = bucket[0] - G
                group_bools = np.pad(group_bools, ((0, pad), (0, 0)))
                group_ints = np.pad(group_ints, ((0, pad), (0, 0)))
        out = np.asarray(
            ktime.dispatch(
                solve_block_jit,
                group_bools,
                group_ints,
                *args,
                kernel="packer.solve_block",
            )
        )[:G]
        return out[:, 0], out[:, 1].astype(bool), out[:, 2], out[:, 3]

    def solve_sharded(self, grouped: GroupedPods, mesh: Mesh, axis: str = "pods"):
        """Multi-chip solve: groups sharded over `axis`, catalog replicated
        (the §7 DP-style layout — collectives only for the final sums).

        The group axis pads to a mesh-size-INVARIANT global shape: the AOT
        ladder's sharded rung when one fits (divisible by the mesh size, so
        every shard gets an equal slab), else pow2 aligned to
        lcm(n, MESH_ALIGN). Padding rows carry counts 0 — they pack to 0
        nodes / 0 unschedulable on whatever shard they land on (an entirely-
        padding shard computes only zeros) and are sliced off before any
        claim is emitted."""
        from karpenter_tpu.aot import ladder as ladder_mod

        n = mesh.shape[axis]
        G = grouped.membership.shape[0]
        group_bools, group_ints = _pack_groups(grouped)

        align = ladder_mod.mesh_multiple(n)
        G2 = max(1 << max(0, (G - 1).bit_length()), align)
        G2 = -(-G2 // align) * align
        ladder = getattr(self.engine, "aot_ladder", None)
        scope = mesh_scope(mesh)
        if ladder is not None:
            bucket = ladder.bucket_for(
                "packer.solve_block_sharded", (G,), multiple_of=n
            )
            if bucket is None:
                # off-ladder: this global shape jit-compiles a sharded
                # executable the warm start never prepaid; the mesh rides
                # the shape label so the event names the layout that missed
                from karpenter_tpu.aot import runtime as aotrt

                aotrt.note_off_ladder(
                    "packer.solve_block_sharded", str(G2), mesh=scope
                )
            else:
                G2 = bucket[0]
        if G2 > G:
            pad = G2 - G
            group_bools = np.pad(group_bools, ((0, pad), (0, 0)))
            group_ints = np.pad(group_ints, ((0, pad), (0, 0)))

        fn = sharded_solve_block(mesh, axis)
        sharding = NamedSharding(mesh, P(axis))
        dev_args = [
            jax.device_put(group_bools, sharding),
            jax.device_put(group_ints, sharding),
        ] + list(self._mesh_catalog_args(mesh))
        out = np.asarray(
            ktime.dispatch(
                fn, *dev_args,
                kernel="packer.solve_block_sharded", aot_scope=scope,
            )
        )
        return (
            out[:G, 0],
            out[:G, 1].astype(bool),
            out[:G, 2],
            out[:G, 3],
        )


# -- the fused FFD scan (the one-dispatch solve) ------------------------------
#
# `_solve_scan` is the monotone FFD scan itself — the host walk's queue,
# emptiest-first claim heap, existing-node scan pointers, claim opening and
# nodepool-limit tracking — reformulated as ONE `lax.while_loop` over the
# count tensors, requirement-family transition tables, and per-claim
# headroom matrices the host builders precompute (ops/fused.py). A steady
# admitted batch therefore executes as ONE device dispatch; the host walk
# remains the semantics oracle and the slow-path fallback.
#
# Decision parity is bit-for-bit: every float comparison runs in float64
# (dispatches are wrapped in `scan_x64()`), subtractions happen per join in
# the host's exact order, and the comparison forms are chosen so they are
# EQUAL to the host's (e.g. the node-capacity gate `int((have+eps)//v) >= 1`
# is equivalent, over the reals the exact Python floordiv computes, to
# `have+eps >= v`). Claim selection reproduces the host heap's
# (count, rank, claim-index) order as an argmin over a packed int64 key.

SCAN_OK = 0
SCAN_CLAIM_OVERFLOW = 1
SCAN_QUEUE_OVERFLOW = 2

_KIND_REJECT, _KIND_SAME, _KIND_NARROW = 0, 1, 2
_SCAN_EPS = 1e-9


@contextmanager
def scan_x64():
    """Scope the fused scan's trace/dispatch under 64-bit mode: the host
    oracle packs/compares float64 and the parity bar is bit-for-bit, so the
    scan must run real f64 on device. Scoped (never global) so every other
    kernel keeps its existing f32/int32 avals, executables, and digests."""
    from jax.experimental import enable_x64

    with enable_x64():
        yield


def _scan_key(count, rank, ci):
    """The host heap key (count, rank, ci) packed into one int64: count and
    rank are bounded by the queue length (< 2**20), ci by the claim bucket
    (< 2**18), so the packing is order-isomorphic to the tuple."""
    return (
        count.astype(jnp.int64) * jnp.int64(1 << 39)
        + (rank.astype(jnp.int64) + jnp.int64(1 << 20)) * jnp.int64(1 << 18)
        + ci.astype(jnp.int64)
    )


# python int (NOT a jnp scalar): int64 avals only exist inside scan_x64(),
# so the constant must stay weakly typed until trace time
_SCAN_KEY_MAX = 1 << 62


def _scan_program(cfg: tuple, args: tuple):
    """The while_loop program as (cond, body) closures. `cfg` is the static
    trace config (T, has_nodes, has_limits); `args` the array operands (see
    fused.py's builder for the full layout contract). Factored out so the
    classic solve, the full-state solve, and the donated warm resume all
    trace the IDENTICAL loop — decision parity across variants is by
    construction, not by test alone."""
    T, has_nodes, has_limits = cfg
    (
        pod_gi,      # [P] i32 — group per pod, host queue order (pad -1)
        claim_pad,   # [C] i32 — shape-only: the claim-axis bucket (content
                     # ignored; an explicit arg so the AOT/observatory shape
                     # signature distinguishes claim capacities)
        g_req,       # [G, D] f64
        g_floor,     # [G, D] f64 — req - 1e-9 (the host fit threshold)
        uniq_alloc,  # [U, D] f64
        usage0,      # [T, D] f64 — daemonset overhead per template
        tol,         # [T, G] bool
        open_ok,     # [T, G] bool — compat ∧ limitless-fit ∧ opening allowed
        open_fam,    # [T, G] i32
        open_uok,    # [T, G, U] bool — limitless fitting unique-alloc rows
        trans_kind,  # [F, G] i8
        trans_fam,   # [F, G] i32 (REJECT rows pinned to 0)
        famu_ok,     # [T, F, U] bool — uid survives tmpl ∧ fam masks
        n_pods,      # () i32
        n_nodes,     # () i32
        node_ok,     # [N, G] bool   (has_nodes)
        node_rem0,   # [N, D] f64    (has_nodes)
        fam_mask,    # [F, I] bool   (has_limits)
        tmpl_mask,   # [T, I] bool   (has_limits)
        open_cand,   # [T, G, I] bool (has_limits)
        uid_onehot,  # [U, I] bool   (has_limits)
        uid_of_type, # [I] i32       (has_limits)
        cap_f,       # [I, D] f64    (has_limits)
        pool_of_t,   # [T] i32       (has_limits; -1 = unlimited)
        pool_rem0,   # [L, D] f64    (has_limits)
        pool_has,    # [L, D] bool   (has_limits)
        pool_bad,    # [L] bool      (has_limits)
    ) = args
    P = pod_gi.shape[0]
    G, D = g_req.shape
    U = uniq_alloc.shape[0]
    i32 = jnp.int32

    def fresh_cfit_row(ti, fam, uv, rem_row, tm_row):
        """cfit[c, :] — 'some valid headroom row of claim c fits group g and
        the requirement transition admits g' — recomputed whenever claim c
        changes. Must equal exactly the per-join keep∧fit evaluation."""
        kindg = trans_kind[fam]            # [G]
        f2g = trans_fam[fam]               # [G]
        if has_limits:
            new_tm = fam_mask[f2g] & tm_row[None, :]          # [G, I]
            keep = feas.uid_project(uid_onehot, new_tm)       # [G, U]
        else:
            keep = famu_ok[ti][f2g]                           # [G, U]
        keep = keep & uv[None, :]
        fits = jnp.all(
            rem_row[None, :, :] >= g_floor[:, None, :], axis=-1
        )                                                     # [G, U]
        return (kindg != _KIND_REJECT) & tol[ti] & jnp.any(keep & fits, axis=-1)

    def body(st):
        (
            head, tail, stop, abort, seqc, done, nclaims,
            queue, last_len, pod_claim, pod_node, pod_seq,
            claim_ti, claim_fam, claim_count, claim_key,
            u_valid, rem, cfit, nptr, node_rem, tm_st, pool_rem,
        ) = st
        pod = queue[head]
        g = pod_gi[pod]
        stop_now = last_len[pod] == (tail - head)

        # -- existing-node scan (host _try_nodes) --
        if has_nodes:
            N = node_ok.shape[0]
            live_n = jnp.arange(N, dtype=i32) >= nptr[g]
            fit_n = jnp.all(
                jnp.where(
                    g_req[g][None, :] > 0,
                    node_rem + _SCAN_EPS >= g_req[g][None, :],
                    True,
                ),
                axis=-1,
            )
            cand_n = live_n & (jnp.arange(N, dtype=i32) < n_nodes) & node_ok[:, g] & fit_n
            any_node = jnp.any(cand_n)
            jn = jnp.argmax(cand_n).astype(i32)
        else:
            any_node = jnp.bool_(False)
            jn = i32(0)

        # -- in-flight claims, emptiest first (host _try_claims) --
        live_c = jnp.arange(claim_key.shape[0], dtype=i32) < nclaims
        cand_c = cfit[:, g] & live_c
        any_claim = (~any_node) & jnp.any(cand_c)
        ci = jnp.argmin(jnp.where(cand_c, claim_key, _SCAN_KEY_MAX)).astype(i32)
        c_ti = claim_ti[ci]
        f2 = trans_fam[claim_fam[ci], g]
        if has_limits:
            new_tm = tm_st[ci] & fam_mask[f2]                 # [I]
            keep_u = feas.uid_project(uid_onehot, new_tm)
        else:
            new_tm = None
            keep_u = famu_ok[c_ti, f2]
        keep_u = keep_u & u_valid[ci]
        fit_u = keep_u & jnp.all(rem[ci] >= g_floor[g][None, :], axis=-1)

        # -- open a new claim (host _new_claim, template order) --
        want_open = (~any_node) & (~any_claim)
        sel_ti = i32(-1)
        sel_uv = jnp.zeros((U,), dtype=bool)
        sel_tm = jnp.zeros((tm_st.shape[1],), dtype=bool) if has_limits else None
        sel_lim = jnp.bool_(False)
        sel_sub = (
            jnp.zeros((pool_rem.shape[0], D)) if has_limits else None
        )
        for ti in range(T):
            ok_t = open_ok[ti, g] & tol[ti, g]
            if has_limits:
                pool = pool_of_t[ti]
                limited = pool >= 0
                pl = jnp.maximum(pool, 0)
                lm = jnp.all(
                    jnp.where(
                        pool_has[pl][None, :],
                        cap_f <= pool_rem[pl][None, :] + _SCAN_EPS,
                        True,
                    ),
                    axis=-1,
                ) & ~pool_bad[pl]                             # [I]
                any_left = jnp.any(lm & tmpl_mask[ti])
                cand_t = open_cand[ti, g] & lm
                live_u = feas.uid_project(uid_onehot, cand_t)
                uv_t = open_uok[ti, g] & jnp.where(limited, live_u, True)
                ok_t = ok_t & jnp.where(
                    limited, any_left & jnp.any(uv_t), True
                )
                tm_t = jnp.where(limited, cand_t, open_cand[ti, g])
                # host _subtract_max: max capacity over the claim's narrowed
                # option set, subtracted from the pool's tracked dims
                surv_types = uv_t[uid_of_type]
                sub_mask = tm_t & surv_types
                maxes = jnp.max(
                    jnp.where(sub_mask[:, None], cap_f, -jnp.inf), axis=0
                )
                maxes = jnp.where(jnp.any(sub_mask), maxes, 0.0)
                sub = (
                    jnp.zeros_like(pool_rem)
                    .at[pl]
                    .add(jnp.where(pool_has[pl] & limited, maxes, 0.0))
                )
            else:
                uv_t = open_uok[ti, g]
                tm_t = None
                limited = jnp.bool_(False)
                sub = None
            take = want_open & ok_t & (sel_ti < 0)
            sel_ti = jnp.where(take, i32(ti), sel_ti)
            sel_uv = jnp.where(take, uv_t, sel_uv)
            if has_limits:
                sel_tm = jnp.where(take, tm_t, sel_tm)
                sel_lim = jnp.where(take, limited, sel_lim)
                sel_sub = jnp.where(take, sub, sel_sub)
        do_open = want_open & (sel_ti >= 0)
        overflow_c = do_open & (nclaims >= jnp.int32(claim_key.shape[0]))
        do_open = do_open & ~overflow_c

        placed = any_node | any_claim | do_open
        failed = (~placed) & (~stop_now)

        # -- commit (all branches merge via row-targeted writes) --
        frozen = stop_now
        adv = ~frozen

        # node commit: the host scan pointer lands on the joined node, or
        # past the end when the scan exhausts (both permanent — monotone)
        if has_nodes:
            nrow = jnp.where(
                any_node & adv, node_rem[jn] - g_req[g], node_rem[jn]
            )
            node_rem = lax.dynamic_update_slice(
                node_rem, nrow[None, :], (jn, i32(0))
            )
            nptr = nptr.at[g].set(
                jnp.where(adv, jnp.where(any_node, jn, n_nodes), nptr[g])
            )

        # claim join/open commit: one target row
        row = jnp.where(any_claim, ci, jnp.where(do_open, nclaims, i32(0)))
        row = jnp.minimum(row, jnp.int32(claim_key.shape[0] - 1))
        touch = (any_claim | do_open) & adv
        seq2 = jnp.where(touch, seqc + 1, seqc)
        open_rem = uniq_alloc - (usage0[jnp.maximum(sel_ti, 0)] + g_req[g])[None, :]
        new_rem = jnp.where(
            any_claim & adv,
            rem[row] - g_req[g][None, :],
            jnp.where(do_open & adv, open_rem, rem[row]),
        )
        new_uv = jnp.where(
            any_claim & adv,
            fit_u,
            jnp.where(do_open & adv, sel_uv, u_valid[row]),
        )
        new_ti = jnp.where(do_open & adv, sel_ti, claim_ti[row])
        new_fam = jnp.where(
            any_claim & adv,
            f2,
            jnp.where(do_open & adv, open_fam[jnp.maximum(sel_ti, 0), g], claim_fam[row]),
        )
        new_count = jnp.where(
            any_claim & adv,
            claim_count[row] + 1,
            jnp.where(do_open & adv, i32(1), claim_count[row]),
        )
        new_rank = jnp.where(
            any_claim & adv,
            -seq2,
            jnp.where(do_open & adv, seq2, i32(0)),
        )
        new_key = jnp.where(
            touch,
            _scan_key(new_count, new_rank, row),
            claim_key[row],
        )
        rem = lax.dynamic_update_slice(rem, new_rem[None], (row, i32(0), i32(0)))
        u_valid = lax.dynamic_update_slice(u_valid, new_uv[None], (row, i32(0)))
        claim_ti = claim_ti.at[row].set(new_ti)
        claim_fam = claim_fam.at[row].set(new_fam)
        claim_count = claim_count.at[row].set(new_count)
        claim_key = claim_key.at[row].set(new_key)
        if has_limits:
            new_tm_row = jnp.where(
                any_claim & adv,
                new_tm,
                jnp.where(do_open & adv, sel_tm, tm_st[row]),
            )
            tm_st = lax.dynamic_update_slice(
                tm_st, new_tm_row[None], (row, i32(0))
            )
            pool_rem = jnp.where(do_open & adv, pool_rem - sel_sub, pool_rem)
        nclaims = jnp.where(do_open & adv, nclaims + 1, nclaims)
        # cfit row refresh for the touched claim (a pure function of the
        # row's state, so refreshing an untouched row 0 is a no-op)
        cfit_row = fresh_cfit_row(
            claim_ti[row], claim_fam[row], u_valid[row], rem[row],
            tm_st[row] if has_limits else None,
        )
        cfit = lax.dynamic_update_slice(cfit, cfit_row[None], (row, i32(0)))

        # pod bookkeeping
        head2 = jnp.where(adv, head + 1, head)
        done2 = jnp.where(placed & adv, done + 1, done)
        pod_claim = pod_claim.at[pod].set(
            jnp.where(any_claim & adv, ci, jnp.where(do_open & adv, row, i32(-1)))
        )
        pod_node = pod_node.at[pod].set(
            jnp.where(any_node & adv, jn, i32(-1)) if has_nodes else i32(-1)
        )
        pod_seq = pod_seq.at[pod].set(
            jnp.where(placed & adv, done, pod_seq[pod])
        )
        # failure: requeue + cycle-detection bookkeeping (host: append, then
        # last_len[pod] = len(queue) - head)
        overflow_q = failed & (tail >= jnp.int32(queue.shape[0]))
        queue = queue.at[jnp.minimum(tail, jnp.int32(queue.shape[0] - 1))].set(
            jnp.where(failed & ~overflow_q, pod, queue[jnp.minimum(tail, jnp.int32(queue.shape[0] - 1))])
        )
        tail2 = jnp.where(failed & ~overflow_q, tail + 1, tail)
        last_len = last_len.at[pod].set(
            jnp.where(failed & adv, tail2 - head2, last_len[pod])
        )
        abort2 = jnp.where(
            overflow_c, i32(SCAN_CLAIM_OVERFLOW),
            jnp.where(overflow_q, i32(SCAN_QUEUE_OVERFLOW), abort),
        )
        stop2 = stop | stop_now
        return (
            head2, tail2, stop2, abort2, seq2, done2, nclaims,
            queue, last_len, pod_claim, pod_node, pod_seq,
            claim_ti, claim_fam, claim_count, claim_key,
            u_valid, rem, cfit, nptr, node_rem, tm_st, pool_rem,
        )

    def cond(st):
        head, tail, stop, abort = st[0], st[1], st[2], st[3]
        return (head < tail) & (~stop) & (abort == SCAN_OK)

    return cond, body


def _scan_init(cfg: tuple, args: tuple):
    """The cold-start loop state st0 — the 23-component tuple the body
    carries. A completed zero-requeue pass's final state IS this init with
    the prefix's work folded in, which is exactly why the resident state
    can seed a warm resume bit-identically (ops/delta.py)."""
    T, has_nodes, has_limits = cfg
    pod_gi, claim_pad, g_req = args[0], args[1], args[2]
    uniq_alloc, n_pods = args[4], args[13]
    node_rem0, tmpl_mask, pool_rem0 = args[16], args[18], args[24]
    P = pod_gi.shape[0]
    G, D = g_req.shape
    U = uniq_alloc.shape[0]
    i32 = jnp.int32
    Qcap = 4 * P + 64
    C = claim_pad.shape[0]
    i32a = lambda n, v=0: jnp.full((n,), v, dtype=i32)  # noqa: E731
    I = tmpl_mask.shape[1] if has_limits else 1
    init_queue = jnp.concatenate(
        [jnp.arange(P, dtype=i32), i32a(Qcap - P, 0)]
    )
    return (
        i32(0), n_pods.astype(i32), jnp.bool_(False), i32(SCAN_OK),
        i32(0), i32(0), i32(0),
        init_queue, i32a(P, -1), i32a(P, -1), i32a(P, -1), i32a(P, -1),
        i32a(C, 0), i32a(C, 0), i32a(C, 0),
        jnp.full((C,), _SCAN_KEY_MAX, dtype=jnp.int64),
        jnp.zeros((C, U), dtype=bool), jnp.zeros((C, U, D)),
        jnp.zeros((C, G), dtype=bool), i32a(G, 0),
        node_rem0 if has_nodes else jnp.zeros((1, D)),
        jnp.zeros((C, I), dtype=bool),
        pool_rem0 if has_limits else jnp.zeros((1, D)),
    )


# final-state indices the classic 10-output solve exposes
_SCAN_OUT_IDX = (3, 6, 9, 10, 11, 12, 13, 16, 21, 22)


def _scan_finals(out: tuple):
    """(abort, nclaims, pod_claim, pod_node, pod_seq, claim_ti, claim_fam,
    u_valid, tm_st, pool_rem) — the decode subset of the full state."""
    return tuple(out[i] for i in _SCAN_OUT_IDX)


def _solve_scan_core(cfg: tuple, args: tuple):
    cond, body = _scan_program(cfg, args)
    return _scan_finals(lax.while_loop(cond, body, _scan_init(cfg, args)))


def _solve_scan_full_core(cfg: tuple, args: tuple):
    """Cold solve that returns the FULL 23-component final state — the
    residency seed for incremental delta solves (ops/delta.py)."""
    cond, body = _scan_program(cfg, args)
    return lax.while_loop(cond, body, _scan_init(cfg, args))


def _solve_scan_resume_core(cfg: tuple, args: tuple, st: tuple, p_lo):
    """Warm resume: continue the scan from a resident final state with the
    suffix pods [p_lo, n_pods) enqueued. Sound ONLY under the residency
    eligibility contract (ops/delta.py): byte-identical verdict operands, a
    pod stream extending the previous order as an exact prefix, and a
    previous pass that drained with zero requeues — then the resident state
    equals the cold scan's mid-state after the prefix, and resuming is
    bit-identical to a cold solve of the full list. The 23 state operands
    are DONATED (solve_scan_resume_fn): XLA reuses the resident buffers for
    the loop carry instead of copying them — zero loop-state copy growth."""
    cond, body = _scan_program(cfg, args)
    n_pods = args[13]
    (head, tail), rest = st[:2], st[2:]
    queue = st[7]
    i32 = jnp.int32
    Qcap = queue.shape[0]
    # Enqueue the suffix inside the kernel (one scalar operand, no
    # unbounded-shape patch kernel): positions [tail, tail+nsuf) take pod
    # ids p_lo+k. tail + nsuf <= P < Qcap, so clipped out-of-range lanes
    # only rewrite their own current values — well-defined no-ops.
    k = jnp.arange(Qcap, dtype=i32)
    nsuf = jnp.maximum(n_pods.astype(i32) - p_lo.astype(i32), 0)
    idx = jnp.clip(tail + k, 0, Qcap - 1)
    queue = queue.at[idx].set(
        jnp.where(k < nsuf, p_lo.astype(i32) + k, queue[idx])
    )
    st2 = (head, tail + nsuf) + (rest[0], rest[1], rest[2], rest[3], rest[4],
                                 queue) + rest[6:]
    return lax.while_loop(cond, body, st2)


# One jitted scan per static trace config (template count, node/limits
# variants) — shared across engines and with the AOT warm-start walk.
_SOLVE_SCAN_FNS: dict[tuple, object] = {}
_SOLVE_SCAN_FULL_FNS: dict[tuple, object] = {}
_SOLVE_SCAN_RESUME_FNS: dict[tuple, object] = {}
_SHARDED_SCAN_FNS: dict[tuple, object] = {}
_SHARDED_SCAN_FULL_FNS: dict[tuple, object] = {}
_SHARDED_SCAN_RESUME_FNS: dict[tuple, object] = {}

# operand layout constants for the scan variants: 27 verdict/stream
# operands, 23 loop-state components, one p_lo scalar for the resume
SCAN_N_ARGS = 27
SCAN_N_STATE = 23
# the donation signature: every resident state operand of the resume
# variant is donated — carried by AOT plans and executable cache keys
SCAN_RESUME_DONATE = tuple(range(SCAN_N_ARGS, SCAN_N_ARGS + SCAN_N_STATE))


def solve_scan_fn(T: int, has_nodes: bool, has_limits: bool):
    cfg = (T, bool(has_nodes), bool(has_limits))
    fn = _SOLVE_SCAN_FNS.get(cfg)
    if fn is None:
        fn = jax.jit(lambda *args: _solve_scan_core(cfg, args))
        _SOLVE_SCAN_FNS[cfg] = fn
    return fn


def solve_scan_full_fn(T: int, has_nodes: bool, has_limits: bool):
    """Cold scan returning the full 23-component final state — seeds the
    per-engine scan residency (ops/delta.py) when delta solves are on."""
    cfg = (T, bool(has_nodes), bool(has_limits))
    fn = _SOLVE_SCAN_FULL_FNS.get(cfg)
    if fn is None:
        fn = jax.jit(lambda *args: _solve_scan_full_core(cfg, args))
        _SOLVE_SCAN_FULL_FNS[cfg] = fn
    return fn


def solve_scan_resume_fn(T: int, has_nodes: bool, has_limits: bool):
    """Warm resume with the 23 resident state operands DONATED
    (`donate_argnums` — XLA aliases the resident buffers into the loop
    carry in place of a copy). Operand order: the 27 scan args, then the
    23-component state, then the p_lo scalar."""
    cfg = (T, bool(has_nodes), bool(has_limits))
    fn = _SOLVE_SCAN_RESUME_FNS.get(cfg)
    if fn is None:
        fn = jax.jit(
            lambda *ops: _solve_scan_resume_core(
                cfg,
                ops[:SCAN_N_ARGS],
                ops[SCAN_N_ARGS : SCAN_N_ARGS + SCAN_N_STATE],
                ops[SCAN_N_ARGS + SCAN_N_STATE],
            ),
            donate_argnums=SCAN_RESUME_DONATE,
        )
        _SOLVE_SCAN_RESUME_FNS[cfg] = fn
    return fn


def sharded_solve_scan(mesh: Mesh, T: int, has_nodes: bool, has_limits: bool):
    """Mesh twin of the fused scan. The scan is control-flow bound (a
    sequential while_loop), so the mesh twin REPLICATES: every chip runs
    the identical program on replicated operands and the (identical)
    result is taken at emit — mesh engines keep the one-dispatch contract
    with zero cross-chip traffic, and the merge-at-emit contract is
    trivially preserved (all shards already agree)."""
    cfg = (T, bool(has_nodes), bool(has_limits))
    key = (mesh,) + cfg
    fn = _SHARDED_SCAN_FNS.get(key)
    if fn is None:
        fn = jax.jit(
            shard_map(
                lambda *args: _solve_scan_core(cfg, args),
                mesh=mesh,
                in_specs=tuple(P() for _ in range(SCAN_N_ARGS)),
                out_specs=tuple(P() for _ in range(10)),
                **_SHARD_MAP_UNCHECKED,
            )
        )
        _SHARDED_SCAN_FNS[key] = fn
    return fn


def sharded_solve_scan_full(mesh: Mesh, T: int, has_nodes: bool, has_limits: bool):
    """Mesh twin of solve_scan_full_fn: replicated like the classic scan
    (the while_loop is sequential), returning the full 23-component state
    so mesh engines keep the same residency contract."""
    cfg = (T, bool(has_nodes), bool(has_limits))
    key = (mesh,) + cfg
    fn = _SHARDED_SCAN_FULL_FNS.get(key)
    if fn is None:
        fn = jax.jit(
            shard_map(
                lambda *args: _solve_scan_full_core(cfg, args),
                mesh=mesh,
                in_specs=tuple(P() for _ in range(SCAN_N_ARGS)),
                out_specs=tuple(P() for _ in range(SCAN_N_STATE)),
                **_SHARD_MAP_UNCHECKED,
            )
        )
        _SHARDED_SCAN_FULL_FNS[key] = fn
    return fn


def sharded_solve_scan_resume(mesh: Mesh, T: int, has_nodes: bool, has_limits: bool):
    """Mesh twin of solve_scan_resume_fn — the donation signature
    (`SCAN_RESUME_DONATE`) carries over to the sharded executable, so warm
    resumes on a mesh also update the replicated resident state in place."""
    cfg = (T, bool(has_nodes), bool(has_limits))
    key = (mesh,) + cfg
    fn = _SHARDED_SCAN_RESUME_FNS.get(key)
    if fn is None:
        n_ops = SCAN_N_ARGS + SCAN_N_STATE + 1
        fn = jax.jit(
            shard_map(
                lambda *ops: _solve_scan_resume_core(
                    cfg,
                    ops[:SCAN_N_ARGS],
                    ops[SCAN_N_ARGS : SCAN_N_ARGS + SCAN_N_STATE],
                    ops[SCAN_N_ARGS + SCAN_N_STATE],
                ),
                mesh=mesh,
                in_specs=tuple(P() for _ in range(n_ops)),
                out_specs=tuple(P() for _ in range(SCAN_N_STATE)),
                **_SHARD_MAP_UNCHECKED,
            ),
            donate_argnums=SCAN_RESUME_DONATE,
        )
        _SHARDED_SCAN_RESUME_FNS[key] = fn
    return fn


def scatter_add_counts(
    counts: np.ndarray, idx: Sequence[int], amount: int = 1
) -> np.ndarray:
    """Unbuffered scatter-add of `amount` into `counts` at `idx` (duplicate
    indices accumulate, matching `jnp.ndarray.at[].add` semantics), growing
    the vector geometrically when an index lands past the end. This is the
    update primitive behind the topology count tensors (ops/topo_counts.py):
    one placement batch scatters its (group, domain) increments in a single
    call instead of a per-domain dict walk."""
    idx = np.asarray(idx, dtype=np.int64)
    if idx.size == 0:
        return counts
    hi = int(idx.max())
    if hi >= counts.shape[0]:
        grown = np.zeros(max(hi + 1, counts.shape[0] * 2), dtype=counts.dtype)
        grown[: counts.shape[0]] = counts
        counts = grown
    np.add.at(counts, idx, amount)
    return counts


def merge_shard_group_counts(
    shard_group_ids: Sequence[np.ndarray],
    num_groups: int,
    shard_amounts: Optional[Sequence[np.ndarray]] = None,
) -> np.ndarray:
    """Segment-reduce per-shard group-membership streams into ONE global
    [num_groups] count vector — the claim-emission merge for a pod-axis-
    sharded encode, where one group's pods may land on several shards and
    each shard only knows its local tally. Ids past num_groups are padding
    rows (the mesh-alignment remainder) and are MASKED OUT, never counted.
    With `shard_amounts`, entry j of shard s contributes amounts[s][j]
    instead of 1 (pre-reduced per-shard count tensors merge the same way).
    Semantics match np.add.at over the concatenated streams — duplicates
    accumulate, exactly like scatter_add_counts and the host dict walk.
    NOTE: the shipped encode (encode_pods_for_packer) groups on the host
    before sharding, so group counts arrive whole; this is the merge
    primitive for encodes that split the raw pod stream across shards
    (spec'd against the concatenated-scatter oracle in tests/test_mesh.py)."""
    out = np.zeros(num_groups, dtype=np.int64)
    for s, ids in enumerate(shard_group_ids):
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        amounts = (
            np.ones(ids.shape[0], dtype=np.int64)
            if shard_amounts is None
            else np.asarray(shard_amounts[s], dtype=np.int64).reshape(-1)
        )
        keep = (ids >= 0) & (ids < num_groups)
        np.add.at(out, ids[keep], amounts[keep])
    return out


def encode_pods_for_packer(
    engine: CatalogEngine,
    pods_requirements: Sequence[Requirements],
    requests: np.ndarray,
    cache=None,
) -> GroupedPods:
    """Requirements → engine rows → groups (the host-side encode step).
    Requirements objects repeated by identity (one object per workload
    shape) encode once. With a delta `EncodeCache` (ops/delta.py), shapes
    already encoded in PREVIOUS passes reuse their interned row ids,
    membership rows, and key-presence rows — a churn pass re-encodes only
    the shapes it has never seen, and bytes re-encoded are metered."""
    from karpenter_tpu.ops import delta as delta_mod

    if cache is None:
        cache = delta_mod.encode_cache(engine)  # None unless --delta-solve on
    if cache is not None:
        return _encode_pods_delta(engine, pods_requirements, requests, cache)
    shape_of: dict[int, int] = {}
    distinct: list[Requirements] = []
    shape_ids = np.empty(len(pods_requirements), dtype=np.int64)
    for p, reqs in enumerate(pods_requirements):
        sid = shape_of.get(id(reqs))
        if sid is None:
            sid = len(distinct)
            shape_of[id(reqs)] = sid
            distinct.append(reqs)
        shape_ids[p] = sid
    distinct_rows = [engine.rows_for(reqs) for reqs in distinct]
    kp_distinct = engine.key_presence(distinct)
    engine._ensure_rows()

    # Vectorized grouping: unique over (shape id, quantized request row).
    scales = feas.resource_scales(engine.resource_dims)
    requests_q = feas.quantize_resources(requests, ceil=True, scales=scales)
    combined = np.column_stack([shape_ids, requests_q])
    uniq, inverse, counts = np.unique(
        combined, axis=0, return_inverse=True, return_counts=True
    )
    G = uniq.shape[0]
    R = max(1, engine.num_rows)
    membership = np.zeros((G, R), dtype=bool)
    for g in range(G):
        for rid in distinct_rows[int(uniq[g, 0])]:
            membership[g, rid] = True
    return GroupedPods(
        membership=membership,
        requests_q=uniq[:, 1:],
        key_present=kp_distinct[uniq[:, 0].astype(np.int64)],
        counts=counts.astype(np.int32),
        group_of_pod=inverse.astype(np.int32),
    )


def _encode_pods_delta(
    engine: CatalogEngine,
    pods_requirements: Sequence[Requirements],
    requests: np.ndarray,
    cache,
) -> GroupedPods:
    """The incremental encode: per-shape lookups against the cross-pass
    EncodeCache; only cache misses touch `engine.rows_for`/`key_presence`.
    Output is bit-identical to the one-shot encode — the same dedup,
    quantization, and np.unique grouping over the same interned rows."""
    cache.begin_pass()
    shape_of: dict[int, int] = {}
    distinct: list[Requirements] = []
    shape_ids = np.empty(len(pods_requirements), dtype=np.int64)
    for p, reqs in enumerate(pods_requirements):
        sid = shape_of.get(id(reqs))
        if sid is None:
            sid = len(distinct)
            shape_of[id(reqs)] = sid
            distinct.append(reqs)
        shape_ids[p] = sid
    entries = [cache.lookup(engine, reqs, engine.num_rows) for reqs in distinct]
    engine._ensure_rows()

    scales = feas.resource_scales(engine.resource_dims)
    requests_q = feas.quantize_resources(requests, ceil=True, scales=scales)
    combined = np.column_stack([shape_ids, requests_q])
    uniq, inverse, counts = np.unique(
        combined, axis=0, return_inverse=True, return_counts=True
    )
    G = uniq.shape[0]
    R = max(1, engine.num_rows)
    membership = np.zeros((G, R), dtype=bool)
    key_present = np.zeros((G, entries[0][2].shape[0]) if entries else (G, 0), dtype=bool)
    for g in range(G):
        _, mrow, kp = entries[int(uniq[g, 0])]
        membership[g, : mrow.shape[0]] = mrow[:R]
        key_present[g] = kp
    cache.end_pass()
    return GroupedPods(
        membership=membership,
        requests_q=uniq[:, 1:],
        key_present=key_present,
        counts=counts.astype(np.int32),
        group_of_pod=inverse.astype(np.int32),
    )
