"""Incremental delta solves: persistent device-resident solver state.

Production traffic is churn, not cold batches. Every provisioning pass used
to re-encode the whole cluster and re-solve the full pending set even at 1%
pod churn; this module makes solver state a persistent, generation-stamped
DEVICE RESIDENCY that passes update in place instead of rebuilding:

1. **Delta encode** (`EncodeCache`): a content/identity row cache for
   `packer.encode_pods_for_packer` — a pass re-encodes only requirement
   shapes it has never seen; everything else reuses interned row ids,
   membership rows, and key-presence rows. Bytes re-encoded are metered per
   pass, so the steady-state encode cost provably scales with churn, not
   cluster size.

2. **Warm group solves** (`GroupResidency`): per-group solve_block results
   (choice, feasibility, pods-per-node — the count-INDEPENDENT outputs)
   stay device-resident keyed by group content fingerprint. A pass solves
   only the perturbed frontier (new/changed groups) through the core
   kernel, scatter-applies the rows into the resident matrix with a
   DONATED buffer (XLA updates in place, no copy), and finalizes
   nodes/unschedulable from this pass's counts. Group count changes — the
   dominant churn signal — cost zero solve work.

3. **Warm scan residency** (`ScanResidency`): the fused one-dispatch FFD
   scan's loop-carried state (claim headroom matrices, count tensors, the
   claim heap key vector, nodepool budgets) survives between passes as the
   full 23-component final state of `packer.solve_scan_full`. An eligible
   follow-up pass — byte-identical verdict-table operands, a pod stream
   that extends the previous order as an exact prefix, and a previous pass
   that drained without a single requeue — resumes the scan against the
   resident state through `packer.solve_scan_resume`, which DONATES every
   state buffer (the ISSUE's `donate_argnums` contract) and enqueues only
   the new suffix pods. Resumption is bit-identical to a cold solve of the
   full list by construction: the resident state IS the cold scan's
   mid-state after the prefix (zero requeues ⇒ identical queue prefix,
   head, tail, and per-claim state).

Self-check: every N warm passes (`--resolve-full-every`, default 16) the
warm result is compared against a from-scratch re-solve; any divergence
fires a typed event (provisioner: `DeltaSelfCheckDivergence`), drops the
residency, and falls back to the full result — the delta path can be
slower than designed, never wrong.

Invalidation: generation stamps (engine `_computed_rows`, operand content
fingerprints) guard every residency; `invalidate_all(reason)` drops
everything (solverd engine rebuild, crash-recovery restart, topology
rollback/restore), metered by reason.
"""

from __future__ import annotations

import hashlib
import os
import threading
import weakref
from typing import Callable, Optional, Sequence

import numpy as np

from karpenter_tpu.metrics import global_registry

# -- mode + cadence -----------------------------------------------------------

# off (default): no residency — every solve is the cold path and all
# existing digests/benchmarks are byte-stable. on: keep solver state
# device-resident between passes. Tests, the delta bench leg, the
# sustained-churn scenario, and the churn-smoke CI job opt in explicitly
# (KARPENTER_TPU_DELTA=on / --delta-solve on).
DELTA_MODE = os.environ.get("KARPENTER_TPU_DELTA", "off").strip().lower() or "off"

# Self-check cadence: every Nth warm pass ALSO runs a from-scratch re-solve
# and asserts decision identity (--resolve-full-every; 0 = check never).
RESOLVE_FULL_EVERY = int(
    os.environ.get("KARPENTER_TPU_RESOLVE_FULL_EVERY", "16") or 16
)


def delta_enabled() -> bool:
    return DELTA_MODE in ("on", "1", "true")


def configure(
    mode: Optional[str] = None, resolve_full_every: Optional[int] = None
) -> None:
    """Option wiring (operator/sim CLIs): the flag wins over the env."""
    global DELTA_MODE, RESOLVE_FULL_EVERY
    if mode:
        DELTA_MODE = mode.strip().lower()
    if resolve_full_every is not None and resolve_full_every >= 0:
        RESOLVE_FULL_EVERY = int(resolve_full_every)


# -- metering -----------------------------------------------------------------

_PASSES_CTR = global_registry.counter(
    "karpenter_solver_delta_passes_total",
    "delta-solve passes by mode (cold seeds residency, warm resumes it, "
    "warm-check additionally ran the from-scratch self-check)",
    labels=["mode"],
)
_BYTES_CTR = global_registry.counter(
    "karpenter_solver_delta_bytes_reencoded_total",
    "bytes of requirement/membership rows re-encoded (cache misses); a "
    "steady churn pass re-encodes O(churn), not O(cluster)",
)
_ROWS_CTR = global_registry.counter(
    "karpenter_solver_delta_rows_total",
    "encode-cache row lookups by outcome",
    labels=["outcome"],
)
_GROUPS_CTR = global_registry.counter(
    "karpenter_solver_delta_groups_total",
    "resident group-solve slots by outcome (reused vs frontier-solved)",
    labels=["outcome"],
)
_SCAN_CTR = global_registry.counter(
    "karpenter_solver_delta_scan_total",
    "fused-scan residency dispatch outcomes (warm resume vs miss reason)",
    labels=["outcome"],
)
_SELFCHECK_CTR = global_registry.counter(
    "karpenter_solver_delta_selfchecks_total",
    "periodic warm-vs-full identity checks by verdict",
    labels=["outcome"],
)
_INVALIDATE_CTR = global_registry.counter(
    "karpenter_solver_delta_invalidations_total",
    "residency drops by reason",
    labels=["reason"],
)
_RESIDENT_GAUGE = global_registry.gauge(
    "karpenter_solver_delta_resident_bytes",
    "bytes of device-resident solver state held between passes",
)

# plain-dict mirror for report surfaces (sim harness, solverd stats, bench):
# snapshot-and-delta friendly, no label plumbing
COUNTERS: dict[str, int] = {
    "delta_passes_cold": 0,
    "delta_passes_warm": 0,
    "delta_passes_warm_check": 0,
    "delta_bytes_reencoded": 0,
    "delta_rows_reused": 0,
    "delta_rows_encoded": 0,
    "delta_groups_reused": 0,
    "delta_groups_solved": 0,
    "delta_scan_warm": 0,
    "delta_scan_miss": 0,
    "delta_selfchecks_identical": 0,
    "delta_selfchecks_divergent": 0,
    "delta_invalidations": 0,
}
_LOCK = threading.Lock()


def _count(key: str, n: int = 1) -> None:
    with _LOCK:
        COUNTERS[key] = COUNTERS.get(key, 0) + n


def delta_counters() -> dict:
    with _LOCK:
        return dict(COUNTERS)


def note_pass(mode: str) -> None:
    _PASSES_CTR.inc({"mode": mode})
    _count(f"delta_passes_{mode.replace('-', '_')}")


def note_bytes_reencoded(n: int) -> None:
    if n:
        _BYTES_CTR.inc(value=float(n))
        _count("delta_bytes_reencoded", n)


def note_rows(outcome: str, n: int = 1) -> None:
    if n:
        _ROWS_CTR.inc({"outcome": outcome}, value=float(n))
        _count(f"delta_rows_{outcome}", n)


def note_groups(outcome: str, n: int = 1) -> None:
    if n:
        _GROUPS_CTR.inc({"outcome": outcome}, value=float(n))
        _count(f"delta_groups_{outcome}", n)


def note_scan(outcome: str) -> None:
    _SCAN_CTR.inc({"outcome": outcome})
    _count("delta_scan_warm" if outcome == "warm" else "delta_scan_miss")


def note_selfcheck(outcome: str) -> None:
    _SELFCHECK_CTR.inc({"outcome": outcome})
    _count(f"delta_selfchecks_{outcome}")


# -- divergence events --------------------------------------------------------

_DIVERGENCE_SINKS: dict[str, Callable[[str, str], None]] = {}


def on_divergence(fn: Callable[[str, str], None], key: str = "default") -> None:
    """Register a (kernel, detail) sink for self-check divergences — the
    provisioner publishes a typed Warning event through this."""
    _DIVERGENCE_SINKS[key] = fn


def _emit_divergence(kernel: str, detail: str) -> None:
    note_selfcheck("divergent")
    for fn in list(_DIVERGENCE_SINKS.values()):
        try:
            fn(kernel, detail)
        except Exception:  # noqa: BLE001 — telemetry must not fail solves
            pass


# -- residency registry -------------------------------------------------------

# Engine id -> residency. Weak finalizers clean up when an engine is
# collected; invalidate_all drops everything explicitly (solverd engine
# rebuild, crash-recovery restart, rollback/restore pathologies).
_SCAN_RESIDENCIES: dict[int, "ScanResidency"] = {}
_GROUP_RESIDENCIES: dict[int, "GroupResidency"] = {}
_ENCODE_CACHES: dict[int, "EncodeCache"] = {}


def scan_residency(engine) -> "ScanResidency":
    key = id(engine)
    res = _SCAN_RESIDENCIES.get(key)
    if res is None:
        res = ScanResidency()
        _SCAN_RESIDENCIES[key] = res
        weakref.finalize(engine, _SCAN_RESIDENCIES.pop, key, None)
    return res


def group_residency(solver) -> "GroupResidency":
    key = id(solver)
    res = _GROUP_RESIDENCIES.get(key)
    if res is None:
        res = GroupResidency()
        _GROUP_RESIDENCIES[key] = res
        weakref.finalize(solver, _GROUP_RESIDENCIES.pop, key, None)
    return res


def encode_cache(engine) -> Optional["EncodeCache"]:
    """The per-engine cross-pass encode cache (None with delta off).
    `packer.encode_pods_for_packer` picks this up automatically when the
    caller doesn't thread an explicit cache."""
    if not delta_enabled():
        return None
    key = id(engine)
    c = _ENCODE_CACHES.get(key)
    if c is None:
        c = EncodeCache()
        _ENCODE_CACHES[key] = c
        weakref.finalize(engine, _ENCODE_CACHES.pop, key, None)
    return c


def invalidate_all(reason: str) -> None:
    """Drop every residency (engine rebuild, restart recovery, rollback)."""
    dropped = 0
    for res in list(_SCAN_RESIDENCIES.values()):
        dropped += res.invalidate(reason, _registry_sweep=True)
    for res in list(_GROUP_RESIDENCIES.values()):
        dropped += res.invalidate(reason, _registry_sweep=True)
    for c in list(_ENCODE_CACHES.values()):
        c.clear()
    if dropped:
        _INVALIDATE_CTR.inc({"reason": reason}, value=float(dropped))
        _count("delta_invalidations", dropped)
    _update_resident_gauge()


def note_invalidation(reason: str, n: int = 1) -> None:
    _INVALIDATE_CTR.inc({"reason": reason}, value=float(n))
    _count("delta_invalidations", n)


def _update_resident_gauge() -> None:
    total = 0
    for res in _SCAN_RESIDENCIES.values():
        total += res.resident_bytes()
    for res in _GROUP_RESIDENCIES.values():
        total += res.resident_bytes()
    _RESIDENT_GAUGE.set(float(total))


def operand_fingerprint(arrays: Sequence, skip: Sequence[int] = ()) -> str:
    """Content hash over the dispatch operands that must be byte-identical
    for a warm resume to be sound (everything except the pod stream)."""
    h = hashlib.blake2b(digest_size=16)
    skipset = set(skip)
    for i, a in enumerate(arrays):
        if i in skipset:
            continue
        arr = np.asarray(a)
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


# -- delta encode: the content/identity row cache -----------------------------


class EncodeCache:
    """Cross-pass cache for `packer.encode_pods_for_packer`: requirement
    shapes map to their interned row ids, membership row, and key-presence
    row. Object identity is the fast path (one Requirements per workload
    shape, the dedup contract the one-pass encode already relies on); the
    canonical content fingerprint (encoding.requirements_fingerprint) is
    the second level, so churn that rebuilds value-identical shapes every
    pass still reuses rows. Weak references keep the identity level from
    pinning dead workload shapes.

    `begin_pass`/`last_pass` meter bytes re-encoded per pass — the number
    the BENCH_r09 floor pins to churn, not cluster size."""

    # content-map cap: past this the workload-shape universe is churning
    # faster than caching helps — reset and reseed
    MAX_SHAPES = 1 << 16

    def __init__(self):
        self._shapes: dict[int, tuple] = {}  # id -> (wref, rows, mrow, kp)
        # second level: canonical content fingerprint -> (rows, mrow, kp).
        # Identity misses land here, so churn that rebuilds value-identical
        # Requirements objects every pass (watch re-decodes) still reuses
        # the interned rows (encoding.requirements_fingerprint).
        self._by_content: dict[bytes, tuple] = {}
        self._pass_bytes = 0
        self._pass_hits = 0
        self._pass_misses = 0
        self.passes = 0

    def begin_pass(self) -> None:
        self.passes += 1
        self._pass_bytes = 0
        self._pass_hits = 0
        self._pass_misses = 0

    def end_pass(self) -> None:
        note_bytes_reencoded(self._pass_bytes)
        note_rows("reused", self._pass_hits)
        note_rows("encoded", self._pass_misses)

    @property
    def last_pass_bytes(self) -> int:
        return self._pass_bytes

    @property
    def last_pass_hits(self) -> int:
        return self._pass_hits

    @property
    def last_pass_misses(self) -> int:
        return self._pass_misses

    def lookup(self, engine, reqs, num_rows: int):
        """(row_ids, membership_row, kp_row) for one requirement shape.
        Two levels: object identity (free), then canonical content
        fingerprint — value-identical shapes rebuilt by watch churn reuse
        the same interned rows. Membership rows pad forward when the
        engine interns more rows — an old shape can never reference a row
        added after it encoded."""
        ent = self._shapes.get(id(reqs))
        if ent is not None and ent[0]() is reqs:
            rows, mrow, kp = ent[1], ent[2], ent[3]
            if mrow.shape[0] < num_rows:
                mrow = np.pad(mrow, (0, num_rows - mrow.shape[0]))
                self._shapes[id(reqs)] = (ent[0], rows, mrow, kp)
            self._pass_hits += 1
            return rows, mrow, kp
        from karpenter_tpu.ops import encoding

        fp = encoding.requirements_fingerprint(reqs)
        cent = self._by_content.get(fp)
        if cent is not None:
            rows, mrow, kp = cent
            if mrow.shape[0] < num_rows:
                mrow = np.pad(mrow, (0, num_rows - mrow.shape[0]))
                self._by_content[fp] = (rows, mrow, kp)
            self._alias(reqs, rows, mrow, kp)
            self._pass_hits += 1
            return rows, mrow, kp
        rows = tuple(engine.rows_for(reqs))
        kp = engine.key_presence([reqs])[0]
        num_rows = max(num_rows, engine.num_rows)
        mrow = np.zeros(max(1, num_rows), dtype=bool)
        for rid in rows:
            mrow[rid] = True
        if len(self._by_content) >= self.MAX_SHAPES:
            self._by_content.clear()
            note_invalidation("encode-capacity")
        self._by_content[fp] = (rows, mrow, kp)
        self._alias(reqs, rows, mrow, kp)
        self._pass_misses += 1
        self._pass_bytes += mrow.nbytes + kp.nbytes + 8 * len(rows)
        return rows, mrow, kp

    def _alias(self, reqs, rows, mrow, kp) -> None:
        """Register the identity fast path for a shape object (weakly, so
        the cache never pins dead workload shapes)."""
        if len(self._shapes) >= self.MAX_SHAPES:
            dead = [k for k, e in self._shapes.items() if e[0]() is None]
            for k in dead:
                del self._shapes[k]
            if len(self._shapes) >= self.MAX_SHAPES:
                self._shapes.clear()
        try:
            wref = weakref.ref(reqs)
        except TypeError:  # plain objects without weakref support
            wref = lambda r=reqs: r  # noqa: E731 — strong fallback
        self._shapes[id(reqs)] = (wref, rows, mrow, kp)

    def clear(self) -> None:
        self._shapes.clear()
        self._by_content.clear()

    def stats(self) -> dict:
        return {
            "shapes_cached": len(self._by_content),
            "passes": self.passes,
            "last_pass_bytes": self._pass_bytes,
            "last_pass_hits": self._pass_hits,
            "last_pass_misses": self._pass_misses,
        }


# -- warm group solves: resident solve_block core results ---------------------

# Slot cap: past this the fingerprint universe is churning shapes faster
# than residency helps — reset and reseed (metered).
MAX_GROUP_SLOTS = 1 << 14


class GroupResidency:
    """Device-resident per-group core results for GroupSolver, keyed by
    group content fingerprint and stamped by the engine row generation.
    The resident matrix holds ONLY count-independent outputs (choice,
    feasible, pods-per-node): group count changes — pods joining/leaving
    an existing shape, the dominant churn — touch no resident slot."""

    def __init__(self):
        self.core = None  # device [cap, 3] int32
        self.cap = 0
        self.slot_of: dict[bytes, int] = {}
        self.gen = None
        self.passes = 0
        self.warm_passes = 0
        self.last_mode = ""

    def resident_bytes(self) -> int:
        return 0 if self.core is None else int(self.cap * 3 * 4)

    def invalidate(self, reason: str, _registry_sweep: bool = False) -> int:
        had = 1 if self.core is not None else 0
        self.core = None
        self.cap = 0
        self.slot_of.clear()
        self.gen = None
        self.warm_passes = 0
        if had and not _registry_sweep:
            note_invalidation(reason)
            _update_resident_gauge()
        return had

    @staticmethod
    def fingerprints(grouped) -> list[bytes]:
        fps = []
        mem = np.ascontiguousarray(grouped.membership)
        req = np.ascontiguousarray(grouped.requests_q)
        kp = np.ascontiguousarray(grouped.key_present)
        for g in range(mem.shape[0]):
            h = hashlib.blake2b(digest_size=16)
            h.update(mem[g].tobytes())
            h.update(req[g].tobytes())
            h.update(kp[g].tobytes())
            fps.append(h.digest())
        return fps

    def solve(self, solver, grouped):
        """The delta group solve: frontier-only core solves + donated
        scatter into residency + counts finalize. Bit-identical to
        solver._solve_full by construction (same math on the same inputs;
        the periodic self-check enforces it anyway)."""
        import jax.numpy as jnp

        from karpenter_tpu.ops import packer
        from karpenter_tpu.tracing import kernel as ktime

        e = solver.engine
        e._ensure_rows()
        gen = (e._computed_rows, e.num_instances, e.num_offerings)
        if self.gen is not None and self.gen != gen:
            self.invalidate("generation")
        self.gen = gen
        self.passes += 1

        fps = self.fingerprints(grouped)
        G = len(fps)
        missing = [g for g, fp in enumerate(fps) if fp not in self.slot_of]
        if len(self.slot_of) + len(missing) > MAX_GROUP_SLOTS:
            self.invalidate("capacity")
            self.gen = gen
            missing = list(range(G))

        # grow the resident matrix (pow2) before any scatter targets it
        need = len(self.slot_of) + len(missing)
        if need > self.cap:
            new_cap = max(64, 1 << max(0, (need - 1).bit_length()))
            grown = jnp.zeros((new_cap, 3), dtype=jnp.int32)
            if self.core is not None and self.cap:
                grown = grown.at[: self.cap].set(self.core)
            self.core = grown
            self.cap = new_cap

        mode = "warm" if len(missing) < G else "cold"
        if missing:
            # distinct group IDENTITIES can carry identical content (the
            # encode dedupes Requirements by object identity) — assign one
            # slot per content fingerprint and solve each fingerprint once
            frontier = []
            for g in missing:
                if fps[g] not in self.slot_of:
                    self.slot_of[fps[g]] = len(self.slot_of)
                    frontier.append(g)
            missing = frontier
        if missing:
            slots = np.array([self.slot_of[fps[g]] for g in missing], np.int32)
            group_bools, group_ints = packer._pack_groups(grouped)
            sub_bools = group_bools[missing]
            sub_ints = group_ints[missing]
            # pad the frontier to the solve_block ladder geometry so the
            # steady executable set stays finite (zero-recompile contract)
            Gf = len(missing)
            Gb = _bucket_groups(e, Gf)
            if Gb > Gf:
                pad = Gb - Gf
                # EDGE padding on inputs AND slots: the pad rows solve to
                # the exact values of the last real group, so the scatter's
                # duplicate writes to its slot are same-value collisions —
                # well-defined no-ops
                sub_bools = np.pad(sub_bools, ((0, pad), (0, 0)), mode="edge")
                sub_ints = np.pad(sub_ints, ((0, pad), (0, 0)), mode="edge")
                slots = np.pad(slots, (0, pad), mode="edge")
            rows = ktime.dispatch(
                packer.solve_block_core_jit,
                sub_bools,
                sub_ints,
                *solver._catalog_args(),
                kernel="packer.solve_block_core",
            )
            self.core = ktime.dispatch(
                packer.delta_scatter_rows,
                self.core,
                jnp.asarray(slots),
                rows,
                kernel="packer.delta_scatter",
            )
        note_groups("solved", len(missing))
        note_groups("reused", G - len(missing))

        # gather this pass's group order + finalize against its counts
        order = np.array([self.slot_of[fp] for fp in fps], np.int32)
        counts = grouped.counts.astype(np.int32)
        Gb = _bucket_groups(e, G)
        if Gb > G:
            order = np.pad(order, (0, Gb - G), mode="edge")
            counts = np.pad(counts, (0, Gb - G))
        out = np.asarray(
            ktime.dispatch(
                packer.delta_finalize,
                self.core,
                jnp.asarray(order),
                jnp.asarray(counts),
                kernel="packer.delta_finalize",
            )
        )[:G]
        self.last_mode = mode
        if mode == "warm":
            self.warm_passes += 1
        note_pass(mode)
        _update_resident_gauge()
        result = (out[:, 0], out[:, 1].astype(bool), out[:, 2], out[:, 3])

        # periodic from-scratch self-check: decision identity or drop
        if (
            RESOLVE_FULL_EVERY > 0
            and mode == "warm"
            and self.warm_passes % RESOLVE_FULL_EVERY == 0
        ):
            note_pass("warm-check")
            full = solver._solve_full(grouped)
            if all(np.array_equal(a, b) for a, b in zip(result, full)):
                note_selfcheck("identical")
            else:
                _emit_divergence(
                    "packer.solve_block",
                    f"delta group solve diverged from full re-solve at "
                    f"pass {self.passes} (G={G})",
                )
                self.invalidate("selfcheck-divergence")
                return full
        return result

    def stats(self) -> dict:
        return {
            "slots": len(self.slot_of),
            "capacity": self.cap,
            "passes": self.passes,
            "warm_passes": self.warm_passes,
            "last_mode": self.last_mode,
            "resident_bytes": self.resident_bytes(),
        }


def _bucket_groups(engine, g: int) -> int:
    """Pad a group axis to the solve_block ladder rung (pow2 floor 8 when
    no ladder is attached) — delta kernels share solve_block's geometry so
    the steady-state executable universe stays sealed."""
    ladder = getattr(engine, "aot_ladder", None)
    if ladder is not None:
        bucket = ladder.bucket_for("packer.solve_block", (g,))
        if bucket is not None:
            return int(bucket[0])
    return max(8, 1 << max(0, (int(g) - 1).bit_length()))


# -- warm scan residency: the fused one-dispatch state ------------------------


class ScanResidency:
    """Per-engine residency of the fused FFD scan's full loop-carried
    state. `eligibility` enforces the strict resume contract (see the
    module docstring); `commit` records the post-dispatch state as the
    next pass's warm start. The state tuple is the DONATED operand set of
    `packer.solve_scan_resume` — after a warm dispatch the old buffers are
    dead and the dispatch outputs become the residency."""

    def __init__(self):
        self.state = None  # 23-component device tuple
        self.cfg = None  # (T, has_nodes, has_limits)
        self.shape_key = None  # tuple of state array shapes
        self.ops_fp = None  # operand content hash (pods excluded)
        self.pod_gi = None  # np [Pb] — previous pass's padded pod stream
        self.p_real = 0
        self.extendable = False
        self.warm_passes = 0
        self.passes = 0
        self.last_outcome = ""

    def resident_bytes(self) -> int:
        if self.state is None:
            return 0
        total = 0
        for a in self.state:
            total += int(np.prod(getattr(a, "shape", ()) or (1,))) * int(
                np.dtype(getattr(a, "dtype", np.int32)).itemsize
            )
        return total

    def invalidate(self, reason: str, _registry_sweep: bool = False) -> int:
        had = 1 if self.state is not None else 0
        self.state = None
        self.cfg = None
        self.shape_key = None
        self.ops_fp = None
        self.pod_gi = None
        self.p_real = 0
        self.extendable = False
        self.warm_passes = 0
        if had and not _registry_sweep:
            note_invalidation(reason)
            _update_resident_gauge()
        return had

    def eligibility(self, cfg, shape_key, ops_fp, pod_gi, p_real) -> str:
        """"" when a warm resume is sound; else the miss reason."""
        if self.state is None:
            return "cold"
        if self.cfg != cfg or self.shape_key != shape_key:
            return "rung"
        if not self.extendable:
            return "failures"
        if self.ops_fp != ops_fp:
            return "operands"
        if p_real < self.p_real:
            return "prefix"
        if not np.array_equal(pod_gi[: self.p_real], self.pod_gi[: self.p_real]):
            return "prefix"
        return ""

    def commit(
        self, state, cfg, shape_key, ops_fp, pod_gi, p_real, extendable
    ) -> None:
        self.state = tuple(state)
        self.cfg = cfg
        self.shape_key = shape_key
        self.ops_fp = ops_fp
        self.pod_gi = np.array(pod_gi, copy=True)
        self.p_real = int(p_real)
        self.extendable = bool(extendable)
        self.passes += 1
        _update_resident_gauge()

    def stats(self) -> dict:
        return {
            "resident": self.state is not None,
            "p_real": self.p_real,
            "extendable": self.extendable,
            "passes": self.passes,
            "warm_passes": self.warm_passes,
            "last_outcome": self.last_outcome,
            "resident_bytes": self.resident_bytes(),
        }


# -- debug surface ------------------------------------------------------------


def debug_view() -> dict:
    """/debug/kernels?view=delta: config, counters, and per-residency
    state — the steady-state drill-down for 'why is my pass still slow'."""
    return {
        "mode": DELTA_MODE,
        "enabled": delta_enabled(),
        "resolve_full_every": RESOLVE_FULL_EVERY,
        "counters": delta_counters(),
        "scan_residencies": [r.stats() for r in _SCAN_RESIDENCIES.values()],
        "group_residencies": [r.stats() for r in _GROUP_RESIDENCIES.values()],
        "resident_bytes": sum(
            r.resident_bytes() for r in _SCAN_RESIDENCIES.values()
        )
        + sum(r.resident_bytes() for r in _GROUP_RESIDENCIES.values()),
    }
