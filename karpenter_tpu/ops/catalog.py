"""CatalogEngine: the device-resident instance-type catalog and the lazily
grown requirement-compatibility matrices.

This is the batched execution backend for the reference's
`filterInstanceTypesByRequirements` (scheduling/nodeclaim.go:373-441): a
NodeClaim's instance-type filter becomes

    feasible[p, i] = compat[p, i] & fits[p, i] & has_offering[p, i]

where `compat` is an AND over the pod/nodeclaim's distinct Requirement rows
(computed once per row via `req_rows_vs_sets` and cached), `fits` is a
resource-vector comparison against allocatable, and `has_offering` reduces
offering-level compatibility over each instance type's offerings.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.aot import runtime as aotrt
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.observability import kernels as kobs
from karpenter_tpu.ops import encoding as enc
from karpenter_tpu.ops import feasibility as feas
from karpenter_tpu.tracing import kernel as ktime
from karpenter_tpu.scheduling.requirements import Operator, Requirement, Requirements

DEFAULT_RESOURCE_DIMS = (
    wk.RESOURCE_CPU,
    wk.RESOURCE_MEMORY,
    wk.RESOURCE_EPHEMERAL_STORAGE,
    wk.RESOURCE_PODS,
)


def _req_cache_key(r: Requirement) -> tuple:
    # min_values never affects compat masks, but interned rows feed the
    # solver's canonical requirement families (ops/ffd.py fam_reqs) and the
    # emitted claim requirements — conflating rows that differ only in
    # minValues would stamp one template's minValues onto another's claims.
    return (r.key, r.complement, r.greater_than, r.less_than, frozenset(r.values), r.min_values)


_RTT_CACHE: dict[str, float] = {}

# Deterministic-routing override: when set, device_rtt_s returns this value
# instead of measuring, so the host-vs-device dispatch decision becomes a
# pure function of cube sizes. The simulator pins it (sim/harness.py) so
# same-seed runs — and CI runs on different machines — route identically
# and report["kernels"] dispatch counts stay byte-deterministic.
PINNED_RTT: Optional[float] = None


def device_rtt_s() -> float:
    """Measured round-trip latency of one tiny dispatch+fetch on the default
    backend, cached per process.

    Dispatch is latency-aware (SURVEY §7: "bucketing/padding discipline" —
    and here, transport discipline): against a co-located chip the RTT is
    ~0.1 ms and even small cubes win on device; through a tunneled/remote
    chip an RTT can be ~100 ms and small cubes must take the exact host twin
    instead. Measuring beats guessing — the same binary runs in both worlds.
    """
    if PINNED_RTT is not None:
        return PINNED_RTT
    import jax

    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — no usable backend: never pick device
        return float("inf")
    rtt = _RTT_CACHE.get(backend)
    if rtt is None:
        import time as _time

        try:
            probe = jax.jit(lambda x: x + 1)
            np.asarray(probe(jnp.ones((8,), jnp.float32)))  # compile + warm
            t0 = _time.perf_counter()
            np.asarray(probe(jnp.full((8,), 2.0, jnp.float32)))
            rtt = _time.perf_counter() - t0
        except Exception:  # noqa: BLE001 — broken device: force the host twin
            rtt = float("inf")
        _RTT_CACHE[backend] = rtt
    return rtt


# Host-twin throughput estimates (cells/second), deliberately conservative so
# the device keeps the large cubes. Calibrated on one x86 core with float32
# BLAS for the membership matmuls.
_HOST_MATMUL_CELLS_PER_S = 2.0e9
_HOST_ROW_CELLS_PER_S = 0.5e9

# "device" / "host" pin the dispatch for tests and benchmarks; None = adaptive.
FORCE_BACKEND: Optional[str] = None

# Row batches below this stay on the exact numpy twin REGARDLESS of the RTT
# cost model. The row kernel's inputs are unpadded — the row count and the
# set tables' word capacity vary — so small steady-state dispatches (joint
# requirement rows interned a few per claim family) would compile a fresh
# executable per novel shape, violating the kernel observatory's
# zero-recompile steady-state contract for a ~ms win. Only bulk encodes
# (catalog bootstrap) amortize a compile.
DEVICE_MIN_ROW_BATCH = 32


def _use_device(host_cells: float, cells_per_s: float) -> bool:
    if FORCE_BACKEND == "device":
        return True
    if FORCE_BACKEND == "host":
        return False
    return host_cells / cells_per_s > device_rtt_s()


@dataclass
class Feasibility:
    """Per-(entity, instance-type) feasibility triple plus diagnostics."""

    compat: np.ndarray  # [P, I] bool — requirements intersect
    fits: np.ndarray  # [P, I] bool — resources fit allocatable
    has_offering: np.ndarray  # [P, I] bool — an available offering is compatible

    @property
    def feasible(self) -> np.ndarray:
        return self.compat & self.fits & self.has_offering


class CatalogEngine:
    """Encodes an instance-type catalog onto the device and evaluates batched
    feasibility queries against it.

    Requirement rows are deduplicated: each distinct Requirement is one row
    of the cached `ReqCompat[R, I]` / `OfferCompat[R, O]` matrices, computed
    on first use. Queries supply sets of row ids (per pod / nodeclaim), and
    compatibility is an AND-reduce over rows via a membership matmul.
    """

    def __init__(
        self,
        instance_types: Sequence[InstanceType],
        extra_resources: Sequence[str] = (),
        vocab: Optional[enc.Vocab] = None,
        mesh=None,
    ):
        self.instance_types = list(instance_types)
        self.vocab = vocab or enc.Vocab()
        # jax.sharding.Mesh for multi-chip cube sweeps (pod axis DP); None =
        # single device
        self.mesh = mesh

        names = list(DEFAULT_RESOURCE_DIMS)
        for it in self.instance_types:
            for k in it.capacity:
                if k not in names:
                    names.append(k)
        for k in extra_resources:
            if k not in names:
                names.append(k)
        self.resource_dims = {n: i for i, n in enumerate(names)}

        # Flatten offerings with owner pointers
        self._offerings = []
        owners = []
        for i, it in enumerate(self.instance_types):
            for o in it.offerings:
                self._offerings.append(o)
                owners.append(i)
        self.num_instances = len(self.instance_types)
        self.num_offerings = len(self._offerings)

        # Pre-intern all catalog vocab before sizing arrays
        for it in self.instance_types:
            self.vocab.observe(it.requirements)
        for o in self._offerings:
            self.vocab.observe(o.requirements)

        self._encode_catalog(owners)

        # Requirement-row cache
        self._row_ids: dict[tuple, int] = {}
        self._rows: list[Requirement] = []
        self._computed_rows = 0
        self._req_compat = np.zeros((0, self.num_instances), dtype=bool)
        self._offer_compat = np.zeros((0, self.num_offerings), dtype=bool)
        # Cross-solve caches for the FFD drivers (ops/ffd.py): steady-state
        # provisioner passes re-solve near-identical batches, and these are
        # pure functions of requirement CONTENT (row-id frozensets are
        # interned per engine). joint-mask cache: rowset -> (compat, offer)
        # masks; family-transition cache: (claim rowset, group rowset) ->
        # (kind, joint rowset, canonical joint Requirements). The joint
        # Requirements are shared read-only — driver callers always copy.
        self.solver_joint_cache: dict[frozenset, Optional[tuple]] = {}
        self.solver_fam_trans: dict[tuple, tuple] = {}
        # AOT bucket ladder (aot/ladder.py), attached by aot.warm_start:
        # when set, device dispatches pad their variable axes to ladder
        # buckets so they hit the prepaid executables; None = plain
        # power-of-two padding (the pre-AOT behavior)
        self.aot_ladder = None

    # -- catalog encoding ---------------------------------------------------

    def _encode_catalog(self, owners: list[int]) -> None:
        v = self.vocab
        self._key_capacity = v.key_capacity
        self._word_capacity = v.word_capacity
        self._inst_sets = enc.encode_requirement_sets(
            v,
            [it.requirements for it in self.instance_types],
            key_capacity=self._key_capacity,
            word_capacity=self._word_capacity,
        )
        self._offer_sets = enc.encode_requirement_sets(
            v,
            [o.requirements for o in self._offerings],
            key_capacity=self._key_capacity,
            word_capacity=self._word_capacity,
        )
        self._tables = v.tables()
        self._tables_version = v.version
        self._device_cache: dict[str, jnp.ndarray] = {}

        # float64 so byte-scale memory comparisons match the host oracle
        # exactly (float32 loses ~512B at 8GiB).
        self.allocatable = enc.encode_resource_lists(
            self.resource_dims, [it.allocatable() for it in self.instance_types]
        )
        # Raw capacity for nodepool-limit filtering and pessimistic
        # subtract-max tracking (scheduler.go:670-686 uses it.capacity).
        self.capacity = enc.encode_resource_lists(
            self.resource_dims, [it.capacity for it in self.instance_types]
        )
        self.offering_available = np.array(
            [o.available for o in self._offerings], dtype=bool
        )
        self.offering_price = np.array(
            [o.price for o in self._offerings], dtype=np.float32
        )
        self.offering_owner = np.array(owners, dtype=np.int32)

        # Offering custom-key needs for the Compatible() undefined-label rule
        # (requirements.go:175-191): a non-well-known offering key with an
        # In/Exists-class operator requires the querying set to define it.
        K = self._key_capacity
        self.offering_custom_need = np.zeros((self.num_offerings, K), dtype=bool)
        for j, o in enumerate(self._offerings):
            for r in o.requirements:
                if r.key in wk.WELL_KNOWN_LABELS:
                    continue
                if r.operator in (Operator.NOT_IN, Operator.DOES_NOT_EXIST):
                    continue
                self.offering_custom_need[j, v.key_id(r.key)] = True

        # owner one-hot for offering→instance any-reduce: [O, I]
        self._owner_onehot = np.zeros((self.num_offerings, self.num_instances), dtype=bool)
        self._owner_onehot[np.arange(self.num_offerings), self.offering_owner] = True

    # -- requirement rows ---------------------------------------------------

    def row_id(self, req: Requirement) -> int:
        key = _req_cache_key(req)
        rid = self._row_ids.get(key)
        if rid is None:
            rid = len(self._rows)
            self._row_ids[key] = rid
            self._rows.append(req)
        return rid

    def rows_for(self, reqs: Requirements) -> list[int]:
        return [self.row_id(r) for r in reqs]

    @property
    def num_rows(self) -> int:
        return len(self._rows)

    def value_matrix(self, key: str) -> np.ndarray:
        """[n_values, I] bool — value-membership of each instance type's own
        declared requirement for `key` (types not defining the key contribute
        no values). Feeds the solver's minValues distinct-value counting
        (types.go:190-224: counts union the type-DECLARED values, not the
        query-narrowed ones). Cached per key for the engine's lifetime — the
        catalog is immutable."""
        cache = getattr(self, "_value_matrices", None)
        if cache is None:
            cache = self._value_matrices = {}
        M = cache.get(key)
        if M is None:
            vals: dict[str, int] = {}
            cols: list[tuple[int, int]] = []
            for i, it in enumerate(self.instance_types):
                row = it.requirements.get(key)
                for v in row.values:
                    vi = vals.setdefault(v, len(vals))
                    cols.append((vi, i))
            M = np.zeros((len(vals), self.num_instances), dtype=bool)
            for vi, i in cols:
                M[vi, i] = True
            cache[key] = M
        return M

    def _maybe_reencode(self) -> None:
        """Re-encode the catalog if the vocabulary outgrew the padded
        capacities (rare — capacities grow pow2). Previously computed compat
        matrices remain valid: compatibility depends only on requirement
        semantics, not slot numbering."""
        if (
            self.vocab.key_capacity > self._key_capacity
            or self.vocab.word_capacity > self._word_capacity
        ):
            self._encode_catalog(list(self.offering_owner))

    def _ensure_rows(self) -> None:
        """Compute compat matrices for any rows added since the last call.
        Batches whose estimated host cost exceeds the measured device RTT run
        on device; incremental joint rows use the exact numpy twin."""
        if self._computed_rows == len(self._rows):
            return
        new_rows = self._rows[self._computed_rows :]
        # Interning new rows may grow the vocabulary past the encoded
        # capacities; encode_requirement_rows interns first, then we re-size.
        er = enc.encode_requirement_rows(self.vocab, new_rows, None)
        self._maybe_reencode()
        # New slots may have been interned without outgrowing the padded
        # capacities; the per-slot tables must still reflect them.
        if self.vocab.version != self._tables_version:
            self._tables = self.vocab.tables()
            self._tables_version = self.vocab.version
        if er.mask.shape[1] < self._word_capacity:
            pad = self._word_capacity - er.mask.shape[1]
            er.mask = np.pad(er.mask, ((0, 0), (0, pad)))

        # row kernel work ~ R * (I + O) * G slot-cells on host
        slots = self._word_capacity * 32  # G = word_capacity * WORD value slots
        host_cells = (
            len(new_rows) * (self.num_instances + self.num_offerings) * max(slots, 1)
        )
        # FORCE_BACKEND="device" (the test/bench pin) must still reach the
        # device row kernel for small batches — only adaptive routing gates
        # on the batch size.
        #
        # With delta solves on AND warm device copies of the compat matrices
        # resident, sub-DEVICE_MIN_ROW_BATCH batches also take the device
        # kernel (padded to the same warm 32-rung executable) so the fresh
        # rows can be APPENDED to the resident matrices below — routing tiny
        # churn batches through the host twin would pop the device cache and
        # force an O(cluster) re-upload on the next query.
        from karpenter_tpu.ops import delta as delta_mod

        delta_warm = (
            delta_mod.delta_enabled()
            and FORCE_BACKEND != "host"
            and self.mesh is None
            and "req_compat" in self._device_cache
        )
        on_device = (
            (len(new_rows) >= DEVICE_MIN_ROW_BATCH or FORCE_BACKEND == "device")
            and _use_device(host_cells, _HOST_ROW_CELLS_PER_S)
        ) or delta_warm
        cast = jnp.asarray if on_device else np.asarray
        if on_device:
            kernel = lambda *a: ktime.dispatch(  # noqa: E731 — dispatch shim
                feas.req_rows_vs_sets, *a, kernel="catalog.row_compat"
            )
            # pad the row batch up to its AOT ladder bucket (results for the
            # padding rows are sliced off below): bulk encodes then dispatch
            # the warm-started executable instead of compiling per row count
            if self.aot_ladder is not None:
                bucket = self.aot_ladder.bucket_for(
                    "catalog.row_compat", (len(new_rows),)
                )
                if bucket is None:
                    # pow2-normalized shape key: bounded warning/event
                    # cardinality when many distinct batch sizes overflow
                    # the ladder
                    aotrt.note_off_ladder(
                        "catalog.row_compat",
                        str(1 << max(0, (len(new_rows) - 1).bit_length())),
                    )
                elif bucket[0] > len(new_rows):
                    pad = bucket[0] - len(new_rows)
                    # edge-replicate the last row: a valid row whose
                    # (discarded) results cost nothing extra semantically
                    er.key = np.pad(er.key, (0, pad), mode="edge")
                    er.complement = np.pad(er.complement, (0, pad), mode="edge")
                    er.has_values = np.pad(er.has_values, (0, pad), mode="edge")
                    er.gt = np.pad(er.gt, (0, pad), mode="edge")
                    er.lt = np.pad(er.lt, (0, pad), mode="edge")
                    er.mask = np.pad(er.mask, ((0, pad), (0, 0)), mode="edge")
        else:
            kernel = feas.req_rows_vs_sets_np
            kobs.registry().record_host(
                "catalog.row_compat",
                f"{len(new_rows)}r,{self.num_instances}i,{self.num_offerings}o",
            )
        row_args = (
            cast(er.key),
            cast(er.complement),
            cast(er.has_values),
            cast(er.gt),
            cast(er.lt),
            cast(er.mask),
        )
        tables = (cast(self._tables.slot_key), cast(self._tables.value_int))
        inst = self._inst_sets
        new_inst = np.asarray(
            kernel(
                *row_args,
                cast(inst.present),
                cast(inst.complement),
                cast(inst.has_values),
                cast(inst.gt),
                cast(inst.lt),
                cast(inst.mask),
                *tables,
            )
        )[: len(new_rows)]
        off = self._offer_sets
        if self.num_offerings:
            new_off = np.asarray(
                kernel(
                    *row_args,
                    cast(off.present),
                    cast(off.complement),
                    cast(off.has_values),
                    cast(off.gt),
                    cast(off.lt),
                    cast(off.mask),
                    *tables,
                )
            )[: len(new_rows)]
        else:
            new_off = np.zeros((len(new_rows), 0), dtype=bool)
        self._req_compat = np.concatenate([self._req_compat, new_inst], axis=0)
        self._offer_compat = np.concatenate([self._offer_compat, new_off], axis=0)
        # Rows that constrain NO catalog entry (all-True columns) are
        # identity elements of the AND-reduce; queries prune them so the
        # matmul's row axis stays tiny.
        self._row_trivial = np.concatenate(
            [
                getattr(self, "_row_trivial", np.zeros(0, dtype=bool)),
                new_inst.all(axis=1) & new_off.all(axis=1),
            ]
        )
        self._computed_rows = len(self._rows)
        if delta_warm and (
            self._device_cache["req_compat"].shape[0] + len(new_rows)
            != self._req_compat.shape[0]
        ):
            delta_warm = False  # resident copy out of step — full re-upload
        if delta_warm:
            # delta scatter path: ship ONLY the fresh rows and append them
            # to the resident device matrices — O(churn) upload per pass
            # instead of invalidating and re-uploading the whole catalog
            self._device_cache["req_compat"] = jnp.concatenate(
                [self._device_cache["req_compat"], jnp.asarray(new_inst)],
                axis=0,
            )
            if "offer_compat" in self._device_cache:
                self._device_cache["offer_compat"] = jnp.concatenate(
                    [self._device_cache["offer_compat"], jnp.asarray(new_off)],
                    axis=0,
                )
            delta_mod.note_rows("device_appended", len(new_rows))
        else:
            self._device_cache.pop("req_compat", None)
            self._device_cache.pop("offer_compat", None)

    def _dev(self, name: str, host_array: np.ndarray) -> jnp.ndarray:
        """Device-resident copy of a catalog matrix, uploaded once per
        (re)encode instead of on every query."""
        arr = self._device_cache.get(name)
        if arr is None:
            arr = jnp.asarray(host_array)
            self._device_cache[name] = arr
        return arr

    def _mesh_dev(self, name: str, host_array: np.ndarray):
        """Mesh-replicated copy of a catalog matrix (the _dev analogue for
        sharded sweeps): shipped to every chip once, not per query."""
        key = f"mesh:{name}"
        arr = self._device_cache.get(key)
        if arr is None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            arr = jax.device_put(
                host_array, NamedSharding(self.mesh, PartitionSpec())
            )
            self._device_cache[key] = arr
        return arr

    # -- queries ------------------------------------------------------------

    def key_presence(self, reqs_list: Sequence[Requirements]) -> np.ndarray:
        """[P, K] key-defined matrix for the undefined-label offering rule."""
        for reqs in reqs_list:
            for r in reqs:
                self.vocab.key_id(r.key)
        self._maybe_reencode()
        out = np.zeros((len(reqs_list), self._key_capacity), dtype=bool)
        for i, reqs in enumerate(reqs_list):
            for r in reqs:
                out[i, self.vocab.key_ids[r.key]] = True
        return out

    def masks_for_rows(
        self, rows: Sequence[int], keys: Sequence[str]
    ) -> tuple[np.ndarray, np.ndarray]:
        """Exact (compat[I], has_offering[I]) for ONE requirement set given
        its interned row ids and constrained keys, evaluated host-side from
        the cached per-row matrices.

        Because set compatibility is a per-requirement AND (Intersects:
        every row must intersect independently, requirements.go:248-268),
        AND-ing the cached row vectors of the JOINT requirement set — whose
        rows are the true per-key intersections produced by Requirements.add
        — is bit-identical to the host filter, including the per-offering
        cross-key conjunction. Hostname rows may be excluded by callers
        (they cannot constrain catalog entries)."""
        rows = list(rows)
        self._ensure_rows()
        if rows:
            compat = self._req_compat[rows].all(axis=0)
        else:
            compat = np.ones(self.num_instances, dtype=bool)
        if self.num_offerings == 0:
            return compat, np.zeros(self.num_instances, dtype=bool)
        if rows:
            offer_rows_ok = self._offer_compat[rows].all(axis=0)
        else:
            offer_rows_ok = np.ones(self.num_offerings, dtype=bool)
        key_present = np.zeros(self._key_capacity, dtype=bool)
        for k in keys:
            kid = self.vocab.key_ids.get(k)
            if kid is not None:
                key_present[kid] = True
        undef_ok = ~np.any(self.offering_custom_need & ~key_present[None, :], axis=1)
        offer_ok = offer_rows_ok & undef_ok & self.offering_available
        has_offering = np.zeros(self.num_instances, dtype=bool)
        np.logical_or.at(has_offering, self.offering_owner[offer_ok], True)
        return compat, has_offering

    def host_masks(self, reqs: Requirements) -> tuple[np.ndarray, np.ndarray]:
        return self.masks_for_rows(self.rows_for(reqs), [r.key for r in reqs])

    def warmup(self) -> "CatalogEngine":
        """Pay the DOMINANT cold costs before the first real batch: jax
        backend initialization and the device RTT probe (seconds on a real
        TPU — the bulk of the cold pass), plus the catalog's row/compat
        bootstrap. Shape-specific kernel compiles are NOT prepaid — jit
        executables are keyed by the batch's padded cube shape, which is
        unknowable here — so the first batch still pays a few hundred ms
        of residual compile; measured split in bench.py. Idempotent."""
        if getattr(self, "_warmed", False):
            return self
        device_rtt_s()  # backend init + RTT probe: the multi-second part
        probe = Requirements(
            Requirement(wk.LABEL_OS, Operator.EXISTS),
            Requirement(wk.LABEL_ARCH, Operator.EXISTS),
        )
        rows = self.rows_for(probe)
        self._ensure_rows()
        self.feasibility(
            [rows], np.zeros((1, len(self.resource_dims)), dtype=np.float64)
        )
        self._warmed = True
        return self

    def feasibility(
        self,
        row_sets: Sequence[Sequence[int]],
        requests: np.ndarray,  # [P, D] float32 in self.resource_dims order
        key_present: Optional[np.ndarray] = None,  # [P, K]
    ) -> Feasibility:
        """Batched feasibility of P requirement-sets against the catalog.

        The row axis is restricted to the NON-TRIVIAL rows actually used by
        this query, and both axes are padded to power-of-two buckets so the
        jitted kernels hit the compile cache across solves. Dispatch is
        latency-aware (see device_rtt_s): cubes too small to amortize the
        measured device round-trip run through the exact numpy twins."""
        self._ensure_rows()
        P = len(row_sets)
        used = sorted(
            {rid for rows in row_sets for rid in rows if not self._row_trivial[rid]}
        ) if self._computed_rows else []
        colmap = {rid: i for i, rid in enumerate(used)}
        R = max(1, len(used))
        P2 = 1 << max(0, (P - 1).bit_length())
        R2 = 1 << max(0, (R - 1).bit_length())
        # Routing is decided on the PLAIN pow2 dims (identical to pre-AOT
        # behavior); only a sweep that actually goes to the device pads up
        # to its AOT ladder bucket — the host twin must not compute over
        # ladder-inflated matrices, and bucket inflation must not skew the
        # host-vs-device decision.
        host_cells = P2 * R2 * (self.num_instances + self.num_offerings)
        on_device = _use_device(host_cells, _HOST_MATMUL_CELLS_PER_S)
        # The mesh serves the production cube (offerings present); a
        # membership-only engine is a degenerate catalog too small to shard.
        mesh_n = (
            int(np.prod(self.mesh.devices.shape))
            if self.mesh is not None and self.num_offerings
            else 0
        )
        ladder_kernel = (
            "feasibility.cube_sharded"
            if mesh_n
            else (
                "feasibility.cube"
                if self.num_offerings
                else "feasibility.membership"
            )
        )
        if on_device and mesh_n:
            # mesh-size-INVARIANT global entity axis: align the pow2 bucket
            # to lcm(n, MESH_ALIGN), so a 1-device and an 8-device mesh
            # dispatch the SAME padded shape (the mesh changes how it
            # splits, never what it is) and kernel digests stay comparable
            from karpenter_tpu.aot import ladder as ladder_mod

            align = ladder_mod.mesh_multiple(mesh_n)
            P2 = -(-max(P2, align) // align) * align
        if on_device and self.aot_ladder is not None:
            # look up by the RAW dims, not the pow2-inflated ones: a tuned
            # ladder may carry non-power-of-two buckets, and (P2, R2) would
            # make them unreachable. A mesh constrains the entity axis to
            # buckets its devices split evenly.
            bucket = self.aot_ladder.bucket_for(
                ladder_kernel, (P, R), multiple_of=mesh_n or 1
            )
            if bucket is None:
                # past the largest bucket (or a ladder with no rung this
                # mesh divides): keep pow2 padding and flag it — this
                # dispatch jit-compiles a shape the warm start never
                # prepaid (the ladder-tuning signal). The mesh rides the
                # label so the warning names the device layout that missed.
                aotrt.note_off_ladder(
                    ladder_kernel,
                    f"{P2}x{R2}",
                    mesh=feas.mesh_scope(self.mesh) if mesh_n else "",
                )
            else:
                P2, R2 = bucket
        membership = np.zeros((P2, R2), dtype=bool)
        for p, rows in enumerate(row_sets):
            for rid in rows:
                i = colmap.get(rid)
                if i is not None:
                    membership[p, i] = True

        req_compat_h = np.zeros((R2, self.num_instances), dtype=bool)
        if used:
            req_compat_h[:R] = self._req_compat[used]
        # fits stays host-side in float64: exact parity with resources.fits
        # at byte magnitudes; it's an O(P*I*D) elementwise op, not the matmul.
        fits = np.all(
            requests.astype(np.float64)[:, None, :]
            <= self.allocatable[None, :, :] + 1e-9,
            axis=-1,
        )

        if key_present is None:
            key_present = np.zeros((P, self._key_capacity), dtype=bool)
        key_present_p = np.zeros((P2, key_present.shape[1]), dtype=bool)
        key_present_p[:P] = key_present
        offer_compat_h = np.zeros((R2, self.num_offerings), dtype=bool)
        if used and self.num_offerings:
            offer_compat_h[:R] = self._offer_compat[used]

        if on_device:
            if self.num_offerings == 0:
                compat = np.asarray(
                    ktime.dispatch(
                        feas.membership_all,
                        jnp.asarray(membership),
                        jnp.asarray(req_compat_h),
                        kernel="feasibility.membership",
                    )
                )[:P]
                return Feasibility(
                    compat, fits, np.zeros((P, self.num_instances), dtype=bool)
                )
            # ONE fused dispatch (both matmuls + offering reduce): through a
            # tunneled chip the round-trip dominates, so program count is the
            # cost model. With a mesh, the entity axis shards across chips.
            if mesh_n:
                # entity axis already aligned to the mesh above; commit the
                # per-query arrays with their intended shardings so the
                # dispatch matches the AOT-compiled input layout exactly
                # (entity-sharded queries, replicated catalog — all-gather
                # only when the result leaves the mesh)
                import jax
                from jax.sharding import NamedSharding, PartitionSpec

                axis = self.mesh.axis_names[0]
                shard = NamedSharding(self.mesh, PartitionSpec(axis))
                rep = NamedSharding(self.mesh, PartitionSpec())
                compat_d, offering_d = ktime.dispatch(
                    feas.sharded_cube(self.mesh),
                    jax.device_put(membership, shard),
                    jax.device_put(req_compat_h, rep),
                    jax.device_put(offer_compat_h, rep),
                    self._mesh_dev("custom_need", self.offering_custom_need),
                    jax.device_put(key_present_p, shard),
                    self._mesh_dev("available", self.offering_available),
                    self._mesh_dev("owner_onehot", self._owner_onehot),
                    kernel="feasibility.cube_sharded",
                    aot_scope=feas.mesh_scope(self.mesh),
                )
            else:
                compat_d, offering_d = ktime.dispatch(
                    feas.production_cube,
                    jnp.asarray(membership),
                    jnp.asarray(req_compat_h),
                    jnp.asarray(offer_compat_h),
                    self._dev("custom_need", self.offering_custom_need),
                    jnp.asarray(key_present_p),
                    self._dev("available", self.offering_available),
                    self._dev("owner_onehot", self._owner_onehot),
                    kernel="feasibility.cube",
                )
            return Feasibility(
                np.asarray(compat_d)[:P], fits, np.asarray(offering_d)[:P]
            )

        # host-twin records mirror the device kernel they stand in for, with
        # the SAME bucket key the device dispatch would produce, so the
        # /debug/kernels drill-down shows both sides of the routing decision
        # under one shape bucket
        compat = feas.membership_all_np(membership, req_compat_h)[:P]
        if self.num_offerings == 0:
            kobs.registry().record_host(
                "feasibility.membership",
                kobs.shape_signature((membership, req_compat_h)),
            )
            return Feasibility(
                compat, fits, np.zeros((P, self.num_instances), dtype=bool)
            )
        kobs.registry().record_host(
            "feasibility.cube",
            kobs.shape_signature(
                (
                    membership,
                    req_compat_h,
                    offer_compat_h,
                    self.offering_custom_need,
                    key_present_p,
                    self.offering_available,
                    self._owner_onehot,
                )
            ),
        )
        has_offering = feas.offering_reduce_np(
            membership,
            offer_compat_h,
            self.offering_custom_need,
            key_present_p,
            self.offering_available,
            self.offering_owner,
            self.num_instances,
        )[:P]
        return Feasibility(compat, fits, has_offering)
