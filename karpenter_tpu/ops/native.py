"""Build and load the native FFD steady-state kernel (ffd_kernel.cc).

The shared library is compiled on first use with the system C++ toolchain
and cached beside the source, keyed by a source hash — mirroring how the
reference ships a compiled scheduler core while we stay pip-less. Loading is
best-effort: any failure (no compiler, unwritable dir, exotic platform)
degrades to the pure-Python loop in ops/ffd.py, which computes identical
decisions — BUT it is ~100x slower in steady state, so the degradation is
ALERTED, not just counted: a warning log line fires here the moment the
fallback engages, and the provisioner publishes a Warning event
(NativeKernelUnavailable) so operators see it in the event stream.

Set KARPENTER_TPU_NATIVE=0 to force the Python loop (deliberate — no
alert). Set KARPENTER_TPU_CXX to pin (or poison, in tests) the compiler.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.join(os.path.dirname(__file__), "_native")
_SRC = os.path.join(_DIR, "ffd_kernel.cc")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
_build_error: Optional[str] = None

i32, i64, u8, u64, f64 = (
    ctypes.c_int32,
    ctypes.c_int64,
    ctypes.c_uint8,
    ctypes.c_uint64,
    ctypes.c_double,
)
p_i32 = ctypes.POINTER(i32)
p_i64 = ctypes.POINTER(i64)
p_u8 = ctypes.POINTER(u8)
p_u64 = ctypes.POINTER(u64)
p_f64 = ctypes.POINTER(f64)
voidp = ctypes.c_void_p

ACT_DONE = 0
ACT_NEED_TOL = 1
ACT_NEED_JOIN = 2
ACT_NEED_NEW_CLAIM = 3
ACT_NEED_NODES = 4
ACT_TIMEOUT = 5

JOIN_REJECT = 1
JOIN_SAME = 2
JOIN_NARROW = 3


def _build() -> Optional[str]:
    global _build_error
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    so = os.path.join(_DIR, f"ffd_kernel_{tag}.so")
    if os.path.exists(so):
        return so
    override = os.environ.get("KARPENTER_TPU_CXX")
    compilers = (override,) if override else ("g++", "c++", "clang++")
    tmp = f"{so}.{os.getpid()}.tmp"  # unique per process: concurrent builders
    failures = []
    try:
        for cxx in compilers:
            try:
                r = subprocess.run(
                    [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC],
                    capture_output=True,
                    timeout=120,
                )
            except (OSError, subprocess.TimeoutExpired) as e:
                failures.append(f"{cxx}: {e}")
                continue
            if r.returncode == 0:
                os.replace(tmp, so)
                return so
            failures.append(
                f"{cxx}: exit {r.returncode}: "
                f"{r.stderr.decode(errors='replace')[:200].strip()}"
            )
        _build_error = "; ".join(failures) or "no C++ compiler found"
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _sigs(lib: ctypes.CDLL) -> None:
    lib.kt_new.restype = voidp
    lib.kt_new.argtypes = [
        i32, i32, i32, i32, i32, i32,
        p_i32, p_f64, p_f64, p_i32, p_i32, p_u64, u8, f64,
    ]
    lib.kt_free.argtypes = [voidp]
    lib.kt_set_tol.argtypes = [voidp, i32, i32, u8]
    lib.kt_set_join.argtypes = [voidp, i32, i32, ctypes.c_int8, i32, p_u64]
    lib.kt_add_claim.restype = i32
    lib.kt_add_claim.argtypes = [voidp, i32, i32, i32, i32, p_u64, p_i32, p_f64, i32]
    lib.kt_set_nodes_done.argtypes = [voidp, i32]
    lib.kt_resolve.argtypes = [voidp, i32]
    lib.kt_run.restype = ctypes.c_int
    lib.kt_run.argtypes = [voidp, p_i64]
    lib.kt_timed_out.restype = u8
    lib.kt_timed_out.argtypes = [voidp]
    lib.kt_head.restype = i64
    lib.kt_head.argtypes = [voidp]
    lib.kt_queue_len.restype = i64
    lib.kt_queue_len.argtypes = [voidp]
    lib.kt_queue_tail.argtypes = [voidp, i64, p_i32]
    lib.kt_failed.argtypes = [voidp, p_u8]
    lib.kt_num_claims.restype = i32
    lib.kt_num_claims.argtypes = [voidp]
    lib.kt_export_sizes.argtypes = [voidp, p_i64]
    lib.kt_export.argtypes = [voidp, p_i64, p_u64, p_i32, p_i32, p_i32, p_i32]


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded kernel library, or None when unavailable/disabled."""
    global _lib, _tried, _build_error
    if os.environ.get("KARPENTER_TPU_NATIVE", "1") == "0":
        return None
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        try:
            so = _build()
            if so is not None:
                lib = ctypes.CDLL(so)
                _sigs(lib)
                _lib = lib
        except Exception as e:  # noqa: BLE001 — degrade to the Python loop
            _lib = None
            _build_error = _build_error or f"{type(e).__name__}: {e}"
        if _lib is None:
            if _build_error is None:
                _build_error = "native kernel build failed"
            # alert, don't just degrade: the pure-Python steady-state loop
            # is ~100x slower — operators must see this, not discover it
            # in a latency graph
            from karpenter_tpu.operator import logging as klog

            klog.logger("native").warning(
                "native FFD kernel unavailable; scheduling falls back to "
                "the pure-Python steady-state loop (~100x slower)",
                error=_build_error,
            )
        _tried = True
    return _lib


def build_failure() -> Optional[str]:
    """Why the native kernel is unavailable (None when it loaded, was
    never tried, or was deliberately disabled via KARPENTER_TPU_NATIVE=0).
    The provisioner turns this into a Warning event once per process."""
    if os.environ.get("KARPENTER_TPU_NATIVE", "1") == "0":
        return None
    if not _tried or _lib is not None:
        return None
    return _build_error or "native kernel build failed"
