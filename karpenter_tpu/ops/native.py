"""Build and load the native FFD steady-state kernel (ffd_kernel.cc).

The shared library is compiled on first use with the system C++ toolchain
and cached beside the source, keyed by a source hash — mirroring how the
reference ships a compiled scheduler core while we stay pip-less. Loading is
best-effort: any failure (no compiler, unwritable dir, exotic platform)
degrades to the pure-Python loop in ops/ffd.py, which computes identical
decisions. Set KARPENTER_TPU_NATIVE=0 to force the Python loop.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.join(os.path.dirname(__file__), "_native")
_SRC = os.path.join(_DIR, "ffd_kernel.cc")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

i32, i64, u8, u64, f64 = (
    ctypes.c_int32,
    ctypes.c_int64,
    ctypes.c_uint8,
    ctypes.c_uint64,
    ctypes.c_double,
)
p_i32 = ctypes.POINTER(i32)
p_i64 = ctypes.POINTER(i64)
p_u8 = ctypes.POINTER(u8)
p_u64 = ctypes.POINTER(u64)
p_f64 = ctypes.POINTER(f64)
voidp = ctypes.c_void_p

ACT_DONE = 0
ACT_NEED_TOL = 1
ACT_NEED_JOIN = 2
ACT_NEED_NEW_CLAIM = 3
ACT_NEED_NODES = 4
ACT_TIMEOUT = 5

JOIN_REJECT = 1
JOIN_SAME = 2
JOIN_NARROW = 3


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    so = os.path.join(_DIR, f"ffd_kernel_{tag}.so")
    if os.path.exists(so):
        return so
    tmp = f"{so}.{os.getpid()}.tmp"  # unique per process: concurrent builders
    try:
        for cxx in ("g++", "c++", "clang++"):
            try:
                r = subprocess.run(
                    [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC],
                    capture_output=True,
                    timeout=120,
                )
            except (OSError, subprocess.TimeoutExpired):
                continue
            if r.returncode == 0:
                os.replace(tmp, so)
                return so
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _sigs(lib: ctypes.CDLL) -> None:
    lib.kt_new.restype = voidp
    lib.kt_new.argtypes = [
        i32, i32, i32, i32, i32, i32,
        p_i32, p_f64, p_f64, p_i32, p_i32, p_u64, u8, f64,
    ]
    lib.kt_free.argtypes = [voidp]
    lib.kt_set_tol.argtypes = [voidp, i32, i32, u8]
    lib.kt_set_join.argtypes = [voidp, i32, i32, ctypes.c_int8, i32, p_u64]
    lib.kt_add_claim.restype = i32
    lib.kt_add_claim.argtypes = [voidp, i32, i32, i32, i32, p_u64, p_i32, p_f64, i32]
    lib.kt_set_nodes_done.argtypes = [voidp, i32]
    lib.kt_resolve.argtypes = [voidp, i32]
    lib.kt_run.restype = ctypes.c_int
    lib.kt_run.argtypes = [voidp, p_i64]
    lib.kt_timed_out.restype = u8
    lib.kt_timed_out.argtypes = [voidp]
    lib.kt_head.restype = i64
    lib.kt_head.argtypes = [voidp]
    lib.kt_queue_len.restype = i64
    lib.kt_queue_len.argtypes = [voidp]
    lib.kt_queue_tail.argtypes = [voidp, i64, p_i32]
    lib.kt_failed.argtypes = [voidp, p_u8]
    lib.kt_num_claims.restype = i32
    lib.kt_num_claims.argtypes = [voidp]
    lib.kt_export_sizes.argtypes = [voidp, p_i64]
    lib.kt_export.argtypes = [voidp, p_i64, p_u64, p_i32, p_i32, p_i32, p_i32]


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded kernel library, or None when unavailable/disabled."""
    global _lib, _tried
    if os.environ.get("KARPENTER_TPU_NATIVE", "1") == "0":
        return None
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        try:
            so = _build()
            if so is not None:
                lib = ctypes.CDLL(so)
                _sigs(lib)
                _lib = lib
        except Exception:  # noqa: BLE001 — degrade to the Python loop
            _lib = None
        _tried = True
    return _lib
