"""Device-resident topology counting: per-(group, domain) count tensors and
batched admission gates for the topo-aware solver (SURVEY §7 step 3).

The host oracle keeps per-group occupancy in str-keyed dicts and answers
every candidate probe by rebuilding Requirement objects through
`TopologyGroup.get` (topologygroup.go:205-408). This module keeps the SAME
counts as dense vectors over domain vocabularies interned in
ops/encoding.DomainVocab, updated by scatter-add per placement batch, and
answers the solver's admission probes (min/max-skew, affinity seeding,
anti-affinity emptiness) as masked reductions over those vectors — cached
per count-generation, so a probe between placements is one integer compare
plus one indexed read.

Sync contract (the part that keeps host-decision parity trivially true):

- `TopologyGroup` stamps a fresh `_gen` on every count mutation
  (scheduler/topology.py). A tensor is valid iff its `synced_gen` equals
  the group's stamp.
- The solver's record plans route through `GroupCounts.record`, which
  applies the increment to the host dict (still the single source of
  truth for slow-path oracle calls) and scatters the same batch into the
  tensor, re-aligning the stamp.
- Any out-of-band mutation — host `Topology.record` on existing-node
  joins, relaxation updates, rollback via `Topology.restore_counts` —
  drifts the stamp and the next gate read performs a full resync.

Gate semantics are EXACT mirrors of the reference next-domain selection;
branches whose outcome depends on sorted-domain iteration over mutable
state (pod-affinity self-seeding on non-hostname keys) delegate to the
host oracle rather than approximate it. Counters below feed
ffd.solver_cache_counters for tracing/kernel attribution.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from karpenter_tpu.observability import kernels as kobs
from karpenter_tpu.ops.encoding import DomainVocab
from karpenter_tpu.ops.packer import scatter_add_counts
from karpenter_tpu.scheduler.topology import (
    MAX_SKEW_UNBOUNDED,
    TYPE_AFFINITY,
    TYPE_ANTI_AFFINITY,
    TYPE_SPREAD,
    _count_gen,
)

# Attribution counters (process-cumulative; snapshot + delta per solve via
# ffd.solver_cache_counters → solverd solve spans record them as volatile
# attrs, same as the joint/pack cache hits).
GATE_EVALS = 0  # admission verdicts served from count tensors
GATE_REFRESHES = 0  # masked-reduction recomputes after a count change
ORACLE_CALLS = 0  # verdicts delegated to the host TopologyGroup oracle
RESYNCS = 0  # full tensor rebuilds after out-of-band count mutations

# Above this many domains the masked reductions run vectorized over the
# numpy tensor; below it, scalar loops win (zone/capacity-type vocabularies
# are 2-8 domains and numpy's per-call overhead dominates there).
VECTOR_MIN_DOMAINS = 32


class GroupCounts:
    """Count tensor for one TopologyGroup.

    `counts[i]` is the occupancy of `vocab.domains[i]`; -1 marks a domain
    that left the group (unregister) so membership tests stay O(1) without
    re-indexing the vocabulary. `tensor()` exports the dense non-negative
    vector (absent domains as 0) for batch reductions and debug surfaces.
    """

    __slots__ = ("tg", "vocab", "counts", "synced_gen", "_np")

    def __init__(self, tg):
        self.tg = tg
        self.vocab = DomainVocab()
        self.counts: list[int] = []
        self.synced_gen = -1
        self._np: Optional[np.ndarray] = None
        self.resync()

    # -- sync ----------------------------------------------------------------

    def fresh(self) -> "GroupCounts":
        if self.synced_gen != self.tg._gen:
            self.resync()
        return self

    def resync(self) -> None:
        """Full rebuild from the host dict (out-of-band mutation, rollback,
        or first use). Vocabulary ids are stable across resyncs."""
        global RESYNCS
        RESYNCS += 1
        tg = self.tg
        vocab = self.vocab
        for d in tg.domains:
            vocab.id(d)
        dom = tg.domains
        self.counts = [dom.get(d, -1) for d in vocab.domains]
        self._np = None
        self.synced_gen = tg._gen
        # kernel-observatory record: resyncs are the count-tensor layer's
        # "compile" — rare, full rebuilds whose frequency the observatory
        # tracks per domain-vocabulary size (the hot gate evals stay
        # uninstrumented; they are the thing being protected)
        kobs.registry().record_host("topo_counts.resync", str(len(vocab.domains)))

    # -- updates -------------------------------------------------------------

    def record(self, *domains: str) -> None:
        """Placement-batch record: host dict + tensor scatter, stamps
        re-aligned. The choke point every fast-path record plan uses."""
        tg = self.tg
        drifted = self.synced_gen != tg._gen
        if not drifted and len(domains) == 1:
            # single-domain fast path — the overwhelmingly common placement
            # batch; the host-dict update is inlined (record() semantics)
            d = domains[0]
            dom = tg.domains
            dom[d] = dom.get(d, 0) + 1
            tg.empty_domains.discard(d)
            tg._gen = gen = next(_count_gen)
            counts = self.counts
            i = self.vocab.id(d)
            if i >= len(counts):
                counts.extend([-1] * (i + 1 - len(counts)))
            counts[i] = counts[i] + 1 if counts[i] > 0 else 1
            if self._np is not None:
                self._np = scatter_add_counts(self._np, [i])
            self.synced_gen = gen
            return
        tg.record(*domains)
        if drifted:
            self.resync()
            return
        counts = self.counts
        vocab_id = self.vocab.id
        n = len(counts)
        idx = []
        for d in domains:
            i = vocab_id(d)
            if i >= n:
                counts.extend([-1] * (i + 1 - n))
                n = i + 1
            if counts[i] < 0:
                counts[i] = 1
            else:
                counts[i] += 1
            idx.append(i)
        if self._np is not None:
            self._np = scatter_add_counts(self._np, idx)
        self.synced_gen = tg._gen

    def record_shards(self, shard_domain_batches) -> None:
        """Placement-batch record for a mesh-sharded emit: each shard of
        the pod axis reports the domains its local placements landed in,
        and the increments merge into the tensor by ONE segment reduction
        (merge_shard_counts) — duplicates across shards accumulate exactly
        as the sequential host walk would, so the merged tensor is
        bit-identical to recording the flattened stream domain-by-domain
        (spec'd against the TopologyGroup oracle in tests/test_mesh.py).
        The host dict stays the single source of truth: it absorbs the
        same flattened stream through tg.record. NOTE: today's serving
        scan walks placements sequentially and records through `record`;
        this is the merge primitive for emit paths that produce per-shard
        placement batches (the device-resident scan, ROADMAP item 2)."""
        flat = [d for batch in shard_domain_batches for d in batch]
        if not flat:
            return
        tg = self.tg
        drifted = self.synced_gen != tg._gen
        tg.record(*flat)
        if drifted:
            self.resync()
            return
        counts = self.counts
        vocab_id = self.vocab.id
        idx_batches = []
        for batch in shard_domain_batches:
            ids = []
            for d in batch:
                i = vocab_id(d)
                if i >= len(counts):
                    counts.extend([-1] * (i + 1 - len(counts)))
                # -1 marks an absent domain; first increment revives it at 1
                if counts[i] < 0:
                    counts[i] = 0
                ids.append(i)
            idx_batches.append(np.asarray(ids, dtype=np.int64))
        merged = merge_shard_counts(idx_batches, len(counts))
        for i in np.nonzero(merged)[0]:
            counts[int(i)] += int(merged[i])
        if self._np is not None:
            self._np = None  # rebuilt lazily from the merged host list
        self.synced_gen = tg._gen

    # (no register() counterpart: hostname groups — the only registration
    # path in the solver — stay dict-backed, so registrations go straight
    # to the host group and any tensor resyncs on the gen drift)

    # -- reads ---------------------------------------------------------------

    def count(self, domain: str) -> int:
        """Occupancy of `domain`, -1 when the domain is not in the group."""
        i = self.vocab.lookup(domain)
        if i is None or i >= len(self.counts):
            return -1
        return self.counts[i]

    def tensor(self) -> np.ndarray:
        """Dense int64 occupancy vector over the vocabulary (absent
        domains as 0) — the export surface for batch reductions, tests,
        and /debug introspection."""
        if self._np is None or len(self._np) != len(self.counts):
            self._np = np.maximum(np.asarray(self.counts, dtype=np.int64), 0)
        return self._np


def merge_shard_counts(
    shard_idx_batches, size: int, amount: int = 1
) -> np.ndarray:
    """Segment-reduce per-shard domain-id increment streams into one dense
    [size] vector: the merge-at-emit step of a mesh-sharded placement
    batch. One implementation of the mask-and-scatter semantics
    (ops/packer.merge_shard_group_counts); every kept index contributes
    `amount`. Indices outside [0, size) are padding remainders and
    contribute nothing."""
    from karpenter_tpu.ops.packer import merge_shard_group_counts

    out = merge_shard_group_counts(shard_idx_batches, size)
    return out * amount if amount != 1 else out


def _unconstrained(req) -> bool:
    """Mirror of the host's 'pod domains are Exists' test
    (_domain_min_count): complement with no explicit values or bounds."""
    return (
        req.complement
        and not req.values
        and req.greater_than is None
        and req.less_than is None
    )


class SpreadGate:
    """min/max-skew admission for one (shape group × spread group) pair.

    `ok(domain_id)` answers the host's fast-plan probe
    `tg.get(pod, pod_domains, In[z]).has(z)` for non-hostname keys: z is
    admissible iff it is a known domain and counts[z] (+1 when the pod
    selects itself) minus the min count over the pod-supported domains is
    within maxSkew (topologygroup.go:229-273 + minDomains rule). The
    verdict set over ALL domains is one masked reduction, recomputed only
    when the group's count generation moves.
    """

    __slots__ = ("gc", "pod_domains", "self_sel", "gen", "_bound", "_sup")

    def __init__(self, gc: GroupCounts, pod_domains, self_selecting: bool):
        self.gc = gc
        self.pod_domains = pod_domains
        self.self_sel = 1 if self_selecting else 0
        self.gen = -1
        self._bound = -1  # admissible iff 0 <= counts[id] <= _bound
        self._sup: Optional[list[bool]] = None  # pod-supported mask (static)

    def intern(self, domain: str) -> int:
        return self.gc.vocab.id(domain)

    def _refresh(self) -> None:
        global GATE_REFRESHES
        GATE_REFRESHES += 1
        gc = self.gc.fresh()
        tg = gc.tg
        counts = gc.counts
        n = len(counts)
        pod = self.pod_domains
        if _unconstrained(pod):
            supported_of = None
        else:
            sup = self._sup
            if sup is None or len(sup) < n:
                has = pod.has
                sup = self._sup = [has(d) for d in gc.vocab.domains]
            supported_of = sup
        # masked min over supported present domains (+ supported cardinality
        # for the minDomains override); the verdict over ALL domains then
        # collapses to one bound: admissible iff 0 <= count <= bound
        if n >= VECTOR_MIN_DOMAINS:
            arr = np.asarray(counts, dtype=np.int64)
            present = arr >= 0
            sup_m = (
                present
                if supported_of is None
                else (present & np.asarray(supported_of[:n]))
            )
            n_sup = int(sup_m.sum())
            min_count = int(arr[sup_m].min()) if n_sup else MAX_SKEW_UNBOUNDED
        else:
            min_count = MAX_SKEW_UNBOUNDED
            n_sup = 0
            for i in range(n):
                c = counts[i]
                if c < 0 or (supported_of is not None and not supported_of[i]):
                    continue
                n_sup += 1
                if c < min_count:
                    min_count = c
        if tg.min_domains is not None and n_sup < tg.min_domains:
            min_count = 0
        self._bound = tg.max_skew + min_count - self.self_sel
        self.gen = gc.synced_gen

    def ok(self, domain_id: int) -> bool:
        global GATE_EVALS
        GATE_EVALS += 1
        gc = self.gc
        if self.gen != gc.tg._gen:
            self._refresh()
        counts = gc.counts
        if domain_id >= len(counts):
            return False
        return 0 <= counts[domain_id] <= self._bound


class AntiGate:
    """Anti-affinity admission on non-hostname keys: z is admissible iff it
    is a known, still-empty domain the pod's own row supports
    (topologygroup.go:389-407 over a single-valued node row). Emptiness
    only shrinks during a solve, so verdicts flip at most once."""

    __slots__ = ("gc", "pod_domains", "gen", "_ok")

    def __init__(self, gc: GroupCounts, pod_domains, self_selecting: bool):
        self.gc = gc
        self.pod_domains = pod_domains
        self.gen = -1
        self._ok: list[bool] = []

    def intern(self, domain: str) -> int:
        return self.gc.vocab.id(domain)

    def _refresh(self) -> None:
        global GATE_REFRESHES
        GATE_REFRESHES += 1
        gc = self.gc.fresh()
        has = self.pod_domains.has
        self._ok = [
            c == 0 and has(d)
            for c, d in zip(gc.counts, gc.vocab.domains)
        ]
        self.gen = gc.synced_gen

    def ok(self, domain_id: int) -> bool:
        global GATE_EVALS
        GATE_EVALS += 1
        if self.gen != self.gc.tg._gen:
            self._refresh()
        ok = self._ok
        return domain_id < len(ok) and ok[domain_id]


class AffinityGate:
    """Pod-affinity admission on non-hostname keys. The countable case — z
    is a known domain with matching pods the pod's row supports — is a
    tensor read. The self-seeding branch (nothing matched anywhere, or no
    compatible domain has a match; topologygroup.go:322-343) picks domains
    by sorted iteration over mutable state, so it DELEGATES to the host
    oracle with the shape representative instead of approximating."""

    __slots__ = ("gc", "pod_domains", "self_selecting", "rep", "gen", "_pos", "_seed")

    def __init__(self, gc: GroupCounts, pod_domains, self_selecting: bool, rep):
        self.gc = gc
        self.pod_domains = pod_domains
        self.self_selecting = self_selecting
        self.rep = rep  # shape representative; selects(rep) == selects(pod)
        self.gen = -1
        self._pos: list[bool] = []
        self._seed = False

    def intern(self, domain: str) -> int:
        return self.gc.vocab.id(domain)

    def _refresh(self) -> None:
        global GATE_REFRESHES
        GATE_REFRESHES += 1
        gc = self.gc.fresh()
        has = self.pod_domains.has
        pos = []
        all_empty = True
        any_compat = False
        for c, d in zip(gc.counts, gc.vocab.domains):
            p = c > 0 and has(d)
            pos.append(p)
            if c > 0:
                all_empty = False
                if p:
                    any_compat = True
        self._pos = pos
        self._seed = self.self_selecting and (all_empty or not any_compat)
        self.gen = gc.synced_gen

    def ok_with_row(self, domain_id: int, domain: str, node_row) -> bool:
        global GATE_EVALS, ORACLE_CALLS
        GATE_EVALS += 1
        if self.gen != self.gc.tg._gen:
            self._refresh()
        pos = self._pos
        if domain_id < len(pos) and pos[domain_id]:
            return True
        if not self._seed:
            return False
        # self-seed branch: host-oracle exact (sorted-domain iteration)
        ORACLE_CALLS += 1
        return self.gc.tg.get(self.rep, self.pod_domains, node_row).has(domain)


class HostAffinityGate:
    """Pod-affinity admission on the HOSTNAME key. Hostnames are claim-local
    domains, so this gate reads the host dict directly — one lookup per
    claim — and gen-caches only the GLOBAL self-seed condition (nothing
    matched anywhere / no compatible domain has a match; the hostname
    branch of topologygroup.go:337-353 inserts the claim's own hostname
    exactly then)."""

    __slots__ = ("tg", "pod_domains", "self_selecting", "gen", "_seed")

    def __init__(self, tg, pod_domains, self_selecting: bool):
        self.tg = tg
        self.pod_domains = pod_domains
        self.self_selecting = self_selecting
        self.gen = -1
        self._seed = False

    def ok(self, hostname: str) -> bool:
        global GATE_EVALS, GATE_REFRESHES
        GATE_EVALS += 1
        if not self.pod_domains.has(hostname):
            return False
        tg = self.tg
        if tg.domains.get(hostname, 0) > 0:
            return True
        if not self.self_selecting:
            return False
        if self.gen != tg._gen:
            GATE_REFRESHES += 1
            has = self.pod_domains.has
            self._seed = len(tg.domains) == len(tg.empty_domains) or not any(
                c > 0 and has(d) for d, c in tg.domains.items()
            )
            self.gen = tg._gen
        return self._seed


def build_gate(gc: GroupCounts, pod_domains, self_selecting: bool, rep):
    """Compile the admission gate for one (shape group × topology group)
    pair; the join-plan evaluator calls gate.ok(domain_id) per family."""
    t = gc.tg.type
    if t == TYPE_SPREAD:
        return SpreadGate(gc, pod_domains, self_selecting)
    if t == TYPE_ANTI_AFFINITY:
        return AntiGate(gc, pod_domains, self_selecting)
    assert t == TYPE_AFFINITY
    return AffinityGate(gc, pod_domains, self_selecting, rep)


def gate_counters() -> dict:
    """Cumulative gate/oracle counters (delta two snapshots to attribute
    one solve — same pattern as ffd.solver_cache_counters)."""
    return {
        "topo_gate_evals": GATE_EVALS,
        "topo_gate_refreshes": GATE_REFRESHES,
        "topo_oracle_calls": ORACLE_CALLS,
        "topo_tensor_resyncs": RESYNCS,
    }
