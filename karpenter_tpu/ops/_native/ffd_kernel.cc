// Native steady-state driver for the device-accelerated FFD simulation
// (ops/ffd.py). The per-pod queue loop — per-group lazy heaps over in-flight
// claims, fit checks against each claim's remaining-headroom rows, permanent
// monotone rejections, family-transition application — runs here at ~100ns
// per pod; Python is re-entered only for events that need requirement
// algebra: a (family, group) transition miss, a new-claim opening, or an
// existing-node join. Both sides replay the exact float64 operations of the
// Python loop (IEEE semantics are identical), so decision parity with the
// host oracle (reference scheduler.go:346-401) is preserved bit-for-bit;
// the parity fuzz in tests/test_device_parity.py exercises this path.
//
// Control protocol: kt_run() executes until DONE / TIMEOUT or an action that
// needs Python, communicated via an out[] vector; Python installs the result
// (kt_set_tol / kt_set_join / kt_add_claim / kt_resolve_*) and calls
// kt_run() again — the claims scan restarts for the current pod, which is
// safe because every partial effect (popping stale or dropped heap entries)
// is idempotent.

#include <cstdint>
#include <cstring>
#include <ctime>
#include <unordered_map>
#include <vector>

using std::int32_t;
using std::int64_t;
using std::uint64_t;
using std::uint8_t;

namespace {

constexpr int ACT_DONE = 0;
constexpr int ACT_NEED_TOL = 1;      // out: [pod, gi, ci, ti]
constexpr int ACT_NEED_JOIN = 2;     // out: [pod, gi, ci, fam]
constexpr int ACT_NEED_NEW_CLAIM = 3;  // out: [pod, gi]
constexpr int ACT_NEED_NODES = 4;    // out: [pod, gi]
constexpr int ACT_TIMEOUT = 5;       // out: [head]
constexpr int ACT_ERROR = 6;

constexpr int8_t TOL_UNKNOWN = 0, TOL_OK = 1, TOL_NO = 2;
constexpr int8_t JOIN_REJECT = 1, JOIN_SAME = 2, JOIN_NARROW = 3;

struct HeapItem {
  int64_t count;
  int64_t rank;
  int32_t ci;
};

inline bool heap_less(const HeapItem& a, const HeapItem& b) {
  if (a.count != b.count) return a.count < b.count;
  if (a.rank != b.rank) return a.rank < b.rank;
  return a.ci < b.ci;
}

struct Heap {
  std::vector<HeapItem> v;

  void sift_up(size_t i) {
    while (i > 0) {
      size_t p = (i - 1) / 2;
      if (heap_less(v[i], v[p])) {
        std::swap(v[i], v[p]);
        i = p;
      } else {
        break;
      }
    }
  }
  void sift_down(size_t i) {
    size_t n = v.size();
    for (;;) {
      size_t l = 2 * i + 1, r = l + 1, s = i;
      if (l < n && heap_less(v[l], v[s])) s = l;
      if (r < n && heap_less(v[r], v[s])) s = r;
      if (s == i) break;
      std::swap(v[i], v[s]);
      i = s;
    }
  }
  void push(HeapItem it) {
    v.push_back(it);
    sift_up(v.size() - 1);
  }
  void pop() {
    v[0] = v.back();
    v.pop_back();
    if (!v.empty()) sift_down(0);
  }
  void replace(HeapItem it) {
    v[0] = it;
    sift_down(0);
  }
};

struct Claim {
  int32_t ti;
  int32_t fam;
  int64_t count;
  int64_t rank;
  int32_t M;                    // live unique-alloc rows
  std::vector<double> rem;      // [M, D] row-major headroom
  std::vector<int32_t> u_ids;   // [M]
  std::vector<uint64_t> type_mask;  // [W] bit per instance type
  std::vector<uint8_t> gdrop;   // [G]
  std::vector<uint8_t> gknown;  // [G]
  std::vector<int32_t> members;      // pod indices, join order
  std::vector<int32_t> group_count;  // [G]
  std::vector<int32_t> group_order;  // first-join order of groups
};

struct FamEnt {
  int8_t kind;
  int32_t new_fam;
  std::vector<uint64_t> mask;  // NARROW only: combined compat∧offer bits [W]
};

struct Ctx {
  int32_t P, G, D, U, W;
  std::vector<int32_t> qpods;   // pod indices; retries appended
  int64_t head;
  std::vector<int32_t> pod_group;   // [P]
  std::vector<double> g_req;        // [G*D]
  std::vector<double> g_fit;        // [G*D] fit floors (req - eps)
  std::vector<int32_t> g_ndim;      // [G] nonzero request dims
  std::vector<int32_t> g_didx;      // [G*D] their indices (first g_ndim)
  std::vector<int64_t> last_len;    // [P]
  std::vector<uint8_t> pod_failed;  // [P]
  std::vector<uint64_t> utype_mask;  // [U*W] types per unique-alloc row
  std::vector<Claim> claims;
  std::vector<Heap> heaps;          // [G]
  std::vector<int64_t> gsynced;     // [G]
  std::vector<int8_t> tol;          // [T*G]
  int32_t T;
  std::unordered_map<int64_t, FamEnt> fam_join;
  int64_t seq;
  uint8_t nodes_active;
  std::vector<uint8_t> g_nodes_done;  // [G]
  double deadline;  // CLOCK_MONOTONIC seconds; <0 → none
  int64_t check;    // pods processed since last deadline poll (spans up-calls)
  uint8_t timed_out;
  // resume state: pod currently mid-claims-scan (or -1)
  int32_t cur_pod;
  uint8_t cur_try_nodes_done;
  // scratch
  std::vector<uint8_t> fitrows;
};

double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

// returns: 1 placed, 0 not placed, -1 action pending (ctx->act filled)
int try_claims(Ctx* c, int32_t pod, int32_t gi, int64_t* out, int* act) {
  Heap& heap = c->heaps[gi];
  // lazy sync of claims opened since this group last looked
  int64_t n = int64_t(c->claims.size());
  for (int64_t ci = c->gsynced[gi]; ci < n; ++ci) {
    const Claim& cl = c->claims[ci];
    heap.push({cl.count, cl.rank, int32_t(ci)});
  }
  c->gsynced[gi] = n;

  const double* req = &c->g_req[size_t(gi) * c->D];
  const double* fit = &c->g_fit[size_t(gi) * c->D];
  const int D = c->D, W = c->W;
  // zero-request dims always pass the fit floor (headroom there is
  // >= -eps from claim open and never shrinks), so loops touch only the
  // group's nonzero dims — bit-identical, ~2x fewer double ops
  const int nd = c->g_ndim[gi];
  const int32_t* didx = &c->g_didx[size_t(gi) * c->D];

  while (!heap.v.empty()) {
    HeapItem top = heap.v[0];
    Claim& cl = c->claims[top.ci];
    if (cl.gdrop[gi]) {
      heap.pop();
      continue;
    }
    if (cl.count != top.count || cl.rank != top.rank) {
      heap.replace({cl.count, cl.rank, top.ci});
      continue;
    }
    std::vector<uint8_t>& fitrows = c->fitrows;
    fitrows.assign(size_t(cl.M), 0);
    bool any = false, all = true;
    if (cl.gknown[gi]) {
      for (int32_t r = 0; r < cl.M; ++r) {
        const double* rem = &cl.rem[size_t(r) * D];
        bool ok = true;
        for (int k = 0; k < nd; ++k) {
          int d = didx[k];
          if (!(rem[d] >= fit[d])) {
            ok = false;
            break;
          }
        }
        fitrows[r] = ok;
        any |= ok;
        all &= ok;
      }
      if (!any) {
        cl.gdrop[gi] = 1;
        heap.pop();
        continue;
      }
    } else {
      // first join of this group onto this claim: tolerance gate, then the
      // memoized family transition
      int8_t t = c->tol[size_t(cl.ti) * c->G + gi];
      if (t == TOL_UNKNOWN) {
        out[0] = pod;
        out[1] = gi;
        out[2] = top.ci;
        out[3] = cl.ti;
        *act = ACT_NEED_TOL;
        return -1;
      }
      if (t == TOL_NO) {
        cl.gdrop[gi] = 1;
        heap.pop();
        continue;
      }
      int64_t key = (int64_t(cl.fam) << 32) | uint32_t(gi);
      auto it = c->fam_join.find(key);
      if (it == c->fam_join.end()) {
        out[0] = pod;
        out[1] = gi;
        out[2] = top.ci;
        out[3] = cl.fam;
        *act = ACT_NEED_JOIN;
        return -1;
      }
      const FamEnt& ent = it->second;
      if (ent.kind == JOIN_REJECT) {
        cl.gdrop[gi] = 1;
        heap.pop();
        continue;
      }
      if (ent.kind == JOIN_NARROW) {
        // candidate narrowed mask; keep rows whose unique-alloc id still has
        // a surviving type, then fit-check — mirrors _try_first_join exactly
        std::vector<uint64_t> new_mask((size_t)W, 0);
        for (int w = 0; w < W; ++w)
          new_mask[w] = cl.type_mask[w] & ent.mask[w];
        std::vector<uint8_t> keep(size_t(cl.M), 0);
        any = false;
        for (int32_t r = 0; r < cl.M; ++r) {
          const uint64_t* um = &c->utype_mask[size_t(cl.u_ids[r]) * W];
          bool kr = false;
          for (int w = 0; w < W; ++w) {
            if (new_mask[w] & um[w]) {
              kr = true;
              break;
            }
          }
          keep[r] = kr;
          bool ok = kr;
          if (ok) {
            const double* rem = &cl.rem[size_t(r) * D];
            for (int k = 0; k < nd; ++k) {
              int d = didx[k];
              if (!(rem[d] >= fit[d])) {
                ok = false;
                break;
              }
            }
          }
          fitrows[r] = ok;
          any |= ok;
        }
        if (!any) {
          cl.gdrop[gi] = 1;
          heap.pop();
          continue;
        }
        // commit narrowing: compact to keep, fitrows follows
        int32_t m2 = 0;
        for (int32_t r = 0; r < cl.M; ++r) {
          if (keep[r]) {
            if (m2 != r) {
              std::memcpy(&cl.rem[size_t(m2) * D], &cl.rem[size_t(r) * D],
                          sizeof(double) * D);
              cl.u_ids[m2] = cl.u_ids[r];
            }
            fitrows[m2] = fitrows[r];
            ++m2;
          }
        }
        cl.M = m2;
        cl.rem.resize(size_t(m2) * D);
        cl.u_ids.resize(size_t(m2));
        fitrows.resize(size_t(m2));
        cl.type_mask = std::move(new_mask);
        cl.fam = ent.new_fam;
        cl.gknown[gi] = 1;
        any = all = true;
        for (int32_t r = 0; r < m2; ++r) {
          if (!fitrows[r]) {
            all = false;
            break;
          }
        }
      } else {  // JOIN_SAME
        any = false;
        all = true;
        for (int32_t r = 0; r < cl.M; ++r) {
          const double* rem = &cl.rem[size_t(r) * D];
          bool ok = true;
          for (int k = 0; k < nd; ++k) {
            int d = didx[k];
            if (!(rem[d] >= fit[d])) {
              ok = false;
              break;
            }
          }
          fitrows[r] = ok;
          any |= ok;
          all &= ok;
        }
        if (!any) {
          cl.gdrop[gi] = 1;
          heap.pop();
          continue;
        }
        cl.gknown[gi] = 1;
      }
    }
    // join: subtract the request; rows that no longer fit die permanently
    if (all) {
      for (int32_t r = 0; r < cl.M; ++r) {
        double* rem = &cl.rem[size_t(r) * D];
        for (int k = 0; k < nd; ++k) rem[didx[k]] -= req[didx[k]];
      }
    } else {
      int32_t m2 = 0;
      for (int32_t r = 0; r < cl.M; ++r) {
        if (fitrows[r]) {
          if (m2 != r) {
            std::memcpy(&cl.rem[size_t(m2) * D], &cl.rem[size_t(r) * D],
                        sizeof(double) * D);
            cl.u_ids[m2] = cl.u_ids[r];
          }
          ++m2;
        }
      }
      cl.M = m2;
      cl.rem.resize(size_t(m2) * D);
      cl.u_ids.resize(size_t(m2));
      for (int32_t r = 0; r < m2; ++r) {
        double* rem = &cl.rem[size_t(r) * D];
        for (int k = 0; k < nd; ++k) rem[didx[k]] -= req[didx[k]];
      }
    }
    cl.count = top.count + 1;
    c->seq += 1;
    cl.rank = -c->seq;
    cl.members.push_back(pod);
    if (cl.group_count[gi] == 0) cl.group_order.push_back(gi);
    cl.group_count[gi] += 1;
    heap.replace({cl.count, cl.rank, top.ci});
    return 1;
  }
  return 0;
}

}  // namespace

extern "C" {

Ctx* kt_new(int32_t P, int32_t G, int32_t D, int32_t U, int32_t W, int32_t T,
            const int32_t* pod_group, const double* g_req, const double* g_fit,
            const int32_t* g_ndim, const int32_t* g_didx,
            const uint64_t* utype_mask, uint8_t nodes_active,
            double timeout_s) {
  Ctx* c = new (std::nothrow) Ctx();
  if (!c) return nullptr;
  c->P = P;
  c->G = G;
  c->D = D;
  c->U = U;
  c->W = W;
  c->T = T;
  c->qpods.reserve(size_t(P) + 64);
  for (int32_t i = 0; i < P; ++i) c->qpods.push_back(i);
  c->head = 0;
  c->pod_group.assign(pod_group, pod_group + P);
  c->g_req.assign(g_req, g_req + size_t(G) * D);
  c->g_fit.assign(g_fit, g_fit + size_t(G) * D);
  c->g_ndim.assign(g_ndim, g_ndim + G);
  c->g_didx.assign(g_didx, g_didx + size_t(G) * D);
  c->last_len.assign(size_t(P), -1);
  c->pod_failed.assign(size_t(P), 0);
  c->utype_mask.assign(utype_mask, utype_mask + size_t(U) * W);
  c->heaps.resize(size_t(G));
  c->gsynced.assign(size_t(G), 0);
  c->tol.assign(size_t(T) * G, TOL_UNKNOWN);
  c->seq = 0;
  c->nodes_active = nodes_active;
  c->g_nodes_done.assign(size_t(G), nodes_active ? 0 : 1);
  c->deadline = timeout_s >= 0 ? now_s() + timeout_s : -1.0;
  c->check = 0;
  c->timed_out = 0;
  c->cur_pod = -1;
  c->cur_try_nodes_done = 0;
  return c;
}

void kt_free(Ctx* c) { delete c; }

void kt_set_tol(Ctx* c, int32_t ti, int32_t gi, uint8_t ok) {
  c->tol[size_t(ti) * c->G + gi] = ok ? TOL_OK : TOL_NO;
}

void kt_set_join(Ctx* c, int32_t fam, int32_t gi, int8_t kind, int32_t new_fam,
                 const uint64_t* mask) {
  FamEnt ent;
  ent.kind = kind;
  ent.new_fam = new_fam;
  if (kind == JOIN_NARROW) ent.mask.assign(mask, mask + c->W);
  c->fam_join.emplace((int64_t(fam) << 32) | uint32_t(gi), std::move(ent));
}

// Register a freshly opened claim (Python ran _new_claim). Mirrors _Claim
// construction: count=1, rank=+seq (fresh claims tie-break in opening order),
// the opening pod already a member.
int32_t kt_add_claim(Ctx* c, int32_t ti, int32_t fam, int32_t pod, int32_t gi,
                     const uint64_t* type_mask, const int32_t* u_ids,
                     const double* rem, int32_t M) {
  Claim cl;
  cl.ti = ti;
  cl.fam = fam;
  c->seq += 1;
  cl.count = 1;
  cl.rank = c->seq;
  cl.M = M;
  cl.rem.assign(rem, rem + size_t(M) * c->D);
  cl.u_ids.assign(u_ids, u_ids + M);
  cl.type_mask.assign(type_mask, type_mask + c->W);
  cl.gdrop.assign(size_t(c->G), 0);
  cl.gknown.assign(size_t(c->G), 0);
  cl.gknown[gi] = 1;
  cl.members.push_back(pod);
  cl.group_count.assign(size_t(c->G), 0);
  cl.group_count[gi] = 1;
  cl.group_order.push_back(gi);
  c->claims.push_back(std::move(cl));
  return int32_t(c->claims.size()) - 1;
}

void kt_set_nodes_done(Ctx* c, int32_t gi) { c->g_nodes_done[gi] = 1; }

// outcome of a Python-resolved step for the CURRENT pod:
//   0 — not resolved, continue the pipeline (e.g. node try failed → claims)
//   1 — pod placed (on a node, or via kt_add_claim)
//   2 — pod failed (new-claim error): append to retry queue
void kt_resolve(Ctx* c, int32_t outcome) {
  int32_t pod = c->cur_pod;
  if (pod < 0) return;
  if (outcome == 1) {
    c->pod_failed[pod] = 0;
    c->cur_pod = -1;
    c->cur_try_nodes_done = 0;
  } else if (outcome == 2) {
    c->pod_failed[pod] = 1;
    c->qpods.push_back(pod);
    c->last_len[pod] = int64_t(c->qpods.size()) - c->head;
    c->cur_pod = -1;
    c->cur_try_nodes_done = 0;
  } else {
    c->cur_try_nodes_done = 1;  // nodes tried, fall through to claims
  }
}

int kt_run(Ctx* c, int64_t* out) {
  for (;;) {
    int32_t pod;
    int32_t gi;
    if (c->cur_pod >= 0) {
      pod = c->cur_pod;
      gi = c->pod_group[pod];
    } else {
      if (c->head >= int64_t(c->qpods.size())) return ACT_DONE;
      pod = c->qpods[c->head];
      if (c->last_len[pod] == int64_t(c->qpods.size()) - c->head)
        return ACT_DONE;  // no progress since this pod last failed
      if (c->deadline >= 0 && (++c->check & 0x1FF) == 0 && now_s() > c->deadline) {
        c->timed_out = 1;
        out[0] = c->head;
        return ACT_TIMEOUT;
      }
      c->head += 1;
      c->cur_pod = pod;
      c->cur_try_nodes_done = 0;
      gi = c->pod_group[pod];
    }
    if (c->nodes_active && !c->g_nodes_done[gi] && !c->cur_try_nodes_done) {
      out[0] = pod;
      out[1] = gi;
      return ACT_NEED_NODES;
    }
    int act = 0;
    int r = try_claims(c, pod, gi, out, &act);
    if (r < 0) return act;  // cur_pod stays set; scan restarts on re-entry
    if (r == 1) {
      c->pod_failed[pod] = 0;
      c->cur_pod = -1;
      c->cur_try_nodes_done = 0;
      continue;
    }
    // no claim took it → Python opens a new claim or records the error
    out[0] = pod;
    out[1] = gi;
    return ACT_NEED_NEW_CLAIM;
  }
}

uint8_t kt_timed_out(Ctx* c) { return c->timed_out; }
int64_t kt_head(Ctx* c) { return c->head; }
int64_t kt_queue_len(Ctx* c) { return int64_t(c->qpods.size()); }
void kt_queue_tail(Ctx* c, int64_t from, int32_t* dst) {
  for (int64_t i = from; i < int64_t(c->qpods.size()); ++i)
    dst[i - from] = c->qpods[i];
}
void kt_failed(Ctx* c, uint8_t* dst) {
  std::memcpy(dst, c->pod_failed.data(), size_t(c->P));
}

int32_t kt_num_claims(Ctx* c) { return int32_t(c->claims.size()); }

// bulk readback for emit: one call sizes everything, one call fills the
// caller's flat buffers (per-claim calls cost ~509 x 5 ctypes round trips)
void kt_export_sizes(Ctx* c, int64_t* out) {
  int64_t u = 0, m = 0, g = 0;
  for (const Claim& cl : c->claims) {
    u += cl.M;
    m += int64_t(cl.members.size());
    g += int64_t(cl.group_order.size());
  }
  out[0] = int64_t(c->claims.size());
  out[1] = u;
  out[2] = m;
  out[3] = g;
}

// info layout per claim: [ti, fam, count, M, n_members, n_groups]
void kt_export(Ctx* c, int64_t* info, uint64_t* type_masks, int32_t* u_ids,
               int32_t* members, int32_t* groups, int32_t* counts) {
  int64_t ui = 0, mi = 0, gi2 = 0;
  for (size_t ci = 0; ci < c->claims.size(); ++ci) {
    const Claim& cl = c->claims[ci];
    int64_t* row = info + ci * 6;
    row[0] = cl.ti;
    row[1] = cl.fam;
    row[2] = cl.count;
    row[3] = cl.M;
    row[4] = int64_t(cl.members.size());
    row[5] = int64_t(cl.group_order.size());
    std::memcpy(type_masks + ci * c->W, cl.type_mask.data(),
                sizeof(uint64_t) * c->W);
    std::memcpy(u_ids + ui, cl.u_ids.data(), sizeof(int32_t) * cl.M);
    ui += cl.M;
    std::memcpy(members + mi, cl.members.data(),
                sizeof(int32_t) * cl.members.size());
    mi += int64_t(cl.members.size());
    for (int32_t g : cl.group_order) {
      groups[gi2] = g;
      counts[gi2] = cl.group_count[g];
      ++gi2;
    }
  }
}

}  // extern "C"
