"""Device-accelerated first-fit-decreasing: the TPU fast path the
Provisioner actually executes.

The reference's solver is a per-pod loop — Pop → try existing nodes →
try in-flight claims (emptiest first) → open a new claim from the weighted
templates (scheduler.go:346-401, :451-557). Its hottest inner op is
`filterInstanceTypesByRequirements` over every instance type
(nodeclaim.go:373-441). This module keeps the FFD skeleton host-side but
reshapes the work TPU-first (SURVEY.md §7 step 3):

1. Pods collapse into groups of identical (requirements, requests) shapes —
   a 50k-pod batch is typically a few hundred shapes.
2. ONE fused device call computes the full feasibility cube
   compat ∧ has-offering over [G groups × I instance types]
   (CatalogEngine.feasibility — membership matmuls on the MXU).
3. The sequential FFD loop then runs over G groups (not P pods), operating
   on CLAIM CLASSES — sets of identical in-flight claims — with vectorized
   numpy splits/fills. Claim requirement algebra reuses the exact host
   `Requirements` implementation, so join decisions match the host solver's
   `NodeClaim.can_add` compatibility semantics bit-for-bit.
4. A final batched device verification re-filters every class against its
   ACCUMULATED requirements (set intersection is not pairwise-decomposable:
   per-group feasibility intersection can be looser than joint feasibility).
   Any discrepancy aborts the fast path and the caller falls back to the
   host loop — the fast path never ships a looser answer.

Eligibility is checked first (`eligible`): pods with pod (anti-)affinity,
topology spread, preferred node affinity, host ports, or volumes — and
solves involving reserved capacity or minValues — take the host path, which
remains the semantics oracle.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional, Sequence

import numpy as np

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Pod
from karpenter_tpu.ops import feasibility as feas
from karpenter_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    Requirements,
)
from karpenter_tpu.scheduling.taints import Taints
from karpenter_tpu.utils import resources as res

if TYPE_CHECKING:
    from karpenter_tpu.ops.catalog import CatalogEngine

# Below this batch size the host per-pod loop is comfortably fast and covers
# every feature; the device path's fixed costs don't pay off.
DEVICE_MIN_PODS = 64

# Observability: how often the fast path ran vs fell back (tests assert on
# the module counters; metrics expose them to operators).
DEVICE_SOLVES = 0
DEVICE_FALLBACKS = 0
# Existing-node fill is host-vectorized per group; cap the node count so the
# host compat checks stay off the critical path (large clusters fall back).
DEVICE_MAX_EXISTING = 512


# -- eligibility -------------------------------------------------------------


def eligible(scheduler, pods: Sequence[Pod]) -> bool:
    """True when the device path can reproduce host semantics for this solve
    (solve-level gates; per-pod gates run once per GROUP during grouping)."""
    if scheduler.engine is None:
        return False
    if len(pods) < DEVICE_MIN_PODS:
        return False
    if len(scheduler.existing_nodes) > DEVICE_MAX_EXISTING:
        return False
    # Topology machinery engaged (spread/affinity groups, incl. inverse
    # anti-affinity from cluster pods) → host.
    if getattr(scheduler.topology, "topology_groups", None):
        return False
    # Reserved capacity and minValues interplay stays host-side.
    if scheduler.reserved_capacity_enabled and any(
        o.capacity_type == wk.CAPACITY_TYPE_RESERVED
        for it in scheduler.engine.instance_types
        for o in it.offerings
    ):
        return False
    for nct in scheduler.nodeclaim_templates:
        if nct.requirements.has_min_values():
            return False
    return True


def _group_eligible(pod: Pod) -> bool:
    """Per-shape gates, checked once per distinct pod shape."""
    spec = pod.spec
    aff = spec.affinity
    if aff is not None:
        if aff.pod_affinity is not None or aff.pod_anti_affinity is not None:
            return False
        na = aff.node_affinity
        if na is not None and (na.preferred or len(na.required) > 1):
            return False
    if spec.topology_spread_constraints:
        return False
    if any(c.ports for c in spec.containers):
        return False
    if getattr(spec, "volumes", None):
        return False
    return True


# -- grouping ----------------------------------------------------------------


class _Group:
    __slots__ = (
        "pods", "reqs", "strict_reqs", "requests", "requests_q", "sort_key",
        "placed_existing",
    )

    def __init__(self, pod: Pod, data):
        self.pods: list[Pod] = [pod]
        self.reqs: Requirements = data.requirements
        self.strict_reqs: Requirements = data.strict_requirements
        self.requests: dict = data.requests
        self.requests_q: Optional[np.ndarray] = None
        self.placed_existing = 0
        self.sort_key = (
            -data.requests.get(wk.RESOURCE_CPU, 0.0),
            -data.requests.get(wk.RESOURCE_MEMORY, 0.0),
            pod.metadata.creation_timestamp,
            pod.metadata.uid,
        )


def _raw_sig(pod: Pod) -> tuple:
    """Cheap value-signature over every spec field that can influence an
    ELIGIBLE pod's scheduling: selector, single required affinity term,
    container resources, tolerations, and the eligibility-gate fields
    themselves (so an ineligible pod can never hide inside an eligible
    group). Runs once per pod — keep it allocation-light."""
    spec = pod.spec
    aff = spec.affinity
    aff_sig: tuple = ()
    gates = 0
    if aff is not None:
        if aff.pod_affinity is not None or aff.pod_anti_affinity is not None:
            gates |= 1
        na = aff.node_affinity
        if na is not None:
            if na.preferred:
                gates |= 2
            aff_sig = tuple(
                tuple(
                    (e["key"], e["operator"], tuple(e.get("values", ())))
                    for e in term.match_expressions
                )
                for term in na.required
            )
    if spec.topology_spread_constraints:
        gates |= 4
    if getattr(spec, "volumes", None):
        gates |= 8
    containers = []
    for c in spec.containers:
        containers.append(
            (
                tuple(sorted(c.requests.items())),
                tuple(sorted(c.limits.items())) if c.limits else (),
                len(c.ports),
                c.restart_policy,
            )
        )
    inits = ()
    if spec.init_containers:
        inits = tuple(
            (
                tuple(sorted(c.requests.items())),
                tuple(sorted(c.limits.items())) if c.limits else (),
                c.restart_policy,
            )
            for c in spec.init_containers
        )
    return (
        tuple(sorted(spec.node_selector.items())) if spec.node_selector else (),
        aff_sig,
        gates,
        tuple(containers),
        inits,
        tuple(sorted(spec.overhead.items())) if spec.overhead else (),
        tuple((t.key, t.operator, t.value, t.effect) for t in spec.tolerations)
        if spec.tolerations
        else (),
    )


def _group_pods(scheduler, pods: Sequence[Pod]) -> Optional[list[_Group]]:
    """Collapse pods into value-identical shape groups, ordered by the host
    queue's FFD key (queue.go:72-108). PodData is computed ONCE per group
    and shared into the scheduler's cache — the per-pod host parse is the
    single biggest cost at 50k pods. Returns None when a shape fails the
    per-group eligibility gates (→ host path)."""
    groups: dict[tuple, _Group] = {}
    order: list[_Group] = []
    for pod in pods:
        sig = _raw_sig(pod)
        g = groups.get(sig)
        if g is None:
            if not _group_eligible(pod):
                return None
            scheduler.update_cached_pod_data(pod)
            data = scheduler.cached_pod_data[pod.metadata.uid]
            g = _Group(pod, data)
            groups[sig] = g
            order.append(g)
        else:
            g.pods.append(pod)
            scheduler.cached_pod_data[pod.metadata.uid] = scheduler.cached_pod_data[
                g.pods[0].metadata.uid
            ]
    order.sort(key=lambda g: g.sort_key)
    return order


# -- claim classes -----------------------------------------------------------


class _ClaimClass:
    """`n_claims` identical in-flight NodeClaims: same template, same
    accumulated requirements, same usage, same member-pod composition."""

    __slots__ = (
        "template", "reqs", "types", "usage_q", "pods_per_claim",
        "n_claims", "members",
    )

    def __init__(self, template, reqs, types, usage_q, pods_per_claim, n_claims, members):
        self.template = template
        self.reqs = reqs  # host Requirements — accumulated, exact algebra
        self.types = types  # np.ndarray [I] bool
        self.usage_q = usage_q  # np.ndarray [D] int64 quantized usage
        self.pods_per_claim = pods_per_claim  # int
        self.n_claims = n_claims  # int
        self.members = members  # list[(group_index, pods_of_group_per_claim)]


def _intersect(reqs_a: Requirements, reqs_b: Requirements) -> Requirements:
    out = Requirements(*reqs_a.values())
    out.add(*reqs_b.values())
    return out


def _narrows(base: Requirements, incoming: Requirements) -> bool:
    """True when `incoming` constrains a key `base` already constrains with a
    different value set — the condition under which joint feasibility can be
    strictly tighter than the intersection of per-source feasibilities."""
    for r in incoming:
        if base.has(r.key) and base.get(r.key) != r:
            return True
    return False


class _DeviceSolve:
    def __init__(self, scheduler, pods: Sequence[Pod]):
        self.s = scheduler
        self.engine: "CatalogEngine" = scheduler.engine
        self.pods = pods
        self.pod_errors: dict[Pod, Exception] = {}
        e = self.engine
        self.D = len(e.resource_dims)
        self.scales = feas.resource_scales(e.resource_dims)
        self.alloc_q = feas.quantize_resources(
            e.allocatable, ceil=False, scales=self.scales
        )  # [I, D] int64, floor — conservative vs host float
        self.type_index = {id(it): i for i, it in enumerate(e.instance_types)}
        # name fallback: a content-cache-hit engine holds equal-content types
        # under different object identities
        self._name_index = {it.name: i for i, it in enumerate(e.instance_types)}
        self.classes: list[_ClaimClass] = []
        self.groups: list[_Group] = []
        # Scheduler state is NOT mutated until the final verification passes:
        # a fallback to the host loop must start from pristine state.
        self.existing_fills: list[tuple[int, int, int, int]] = []  # (node, group, start, count)
        self.existing_reqs: dict[int, Requirements] = {}  # live accumulated node reqs
        self.remaining_resources = {
            name: dict(rl) for name, rl in scheduler.remaining_resources.items()
        }
        # Joint-requirement verification is only needed when two sources
        # constrained the SAME key with DIFFERENT value sets — that's the only
        # way per-group feasibility intersection can be looser than joint
        # feasibility (set intersection isn't pairwise-decomposable).
        self.needs_verify = False

    # -- encoding ------------------------------------------------------------

    def _encode(self) -> bool:
        e = self.engine
        groups = _group_pods(self.s, self.pods)
        if groups is None:
            return False
        self.groups = groups
        G = len(self.groups)
        requests = np.zeros((G, self.D), dtype=np.float64)
        for gi, g in enumerate(self.groups):
            for name, v in g.requests.items():
                dim = e.resource_dims.get(name)
                if dim is not None:
                    requests[gi, dim] = v
            g.requests_q = feas.quantize_resources(
                requests[gi], ceil=True, scales=self.scales
            )
        row_sets = [e.rows_for(g.reqs) for g in self.groups]
        key_present = e.key_presence([g.reqs for g in self.groups])
        fz = e.feasibility(row_sets, requests.astype(np.float32), key_present)
        # Free feasibility: compat ∧ offering. Fits is recomputed per class
        # with accumulated usage + daemon overhead (nodeclaim.go:373-441's
        # fits is against the CLAIM's total requests, not the bare pod's).
        self.feas_free = fz.compat & fz.has_offering  # [G, I]
        return True

    def _template_masks(self) -> None:
        """Per-template instance-type masks and group compatibility."""
        s, e = self.s, self.engine
        I = e.num_instances
        T = len(s.nodeclaim_templates)
        self.tmpl_types = np.zeros((T, I), dtype=bool)
        self.tmpl_overhead_q = np.zeros((T, self.D), dtype=np.int64)
        for ti, nct in enumerate(s.nodeclaim_templates):
            for it in nct.instance_type_options:
                idx = self.type_index.get(id(it))
                if idx is None:
                    idx = self._name_index.get(it.name)
                if idx is not None:
                    self.tmpl_types[ti, idx] = True
            overhead = np.zeros(self.D, dtype=np.float64)
            for name, v in s.daemon_overhead[nct].items():
                dim = e.resource_dims.get(name)
                if dim is not None:
                    overhead[dim] = v
            self.tmpl_overhead_q[ti] = feas.quantize_resources(
                overhead, ceil=True, scales=self.scales
            )

    # -- existing-node fill (per-pod: addToExistingNode, earliest index) -----

    def _fill_existing(self) -> None:
        s = self.s
        nodes = s.existing_nodes
        if not nodes:
            return
        N = len(nodes)
        remaining = np.zeros((N, self.D), dtype=np.float64)
        for ni, en in enumerate(nodes):
            for name, v in en.remaining_resources.items():
                dim = self.engine.resource_dims.get(name)
                if dim is not None:
                    remaining[ni, dim] = v
        # Requirement/taint compat cached by node-label signature: clusters
        # have few distinct node shapes, so the host checks stay tiny.
        compat_cache: dict[tuple, bool] = {}
        for gi, g in enumerate(self.groups):
            total = len(g.pods)
            left = total
            for ni, en in enumerate(nodes):
                if left == 0:
                    break
                # Live accumulated requirements: a prior fill that introduced
                # a key narrows what later groups may join (the reference
                # narrows node requirements on every Add). Un-narrowed nodes
                # share a signature-keyed compat cache.
                live_reqs = self.existing_reqs.get(ni)
                if live_reqs is not None:
                    ok = (
                        Taints(en.cached_taints).tolerates_pod(g.pods[0]) is None
                        and live_reqs.compatible(g.reqs) is None
                    )
                else:
                    sig = (
                        tuple(sorted(en.state_node.labels().items())),
                        tuple((t.key, t.value, t.effect) for t in en.cached_taints),
                        gi,
                    )
                    ok = compat_cache.get(sig)
                    if ok is None:
                        ok = (
                            Taints(en.cached_taints).tolerates_pod(g.pods[0]) is None
                            and en.requirements.compatible(g.reqs) is None
                        )
                        compat_cache[sig] = ok
                if not ok:
                    continue
                rem_q = feas.quantize_resources(
                    remaining[ni], ceil=False, scales=self.scales
                )
                if not np.all(rem_q >= 0):
                    continue
                per_dim = np.where(
                    g.requests_q > 0,
                    rem_q // np.maximum(g.requests_q, 1),
                    np.iinfo(np.int64).max,
                )
                fit = int(min(int(np.min(per_dim)), left))
                if fit <= 0:
                    continue
                start = total - left
                self.existing_fills.append((ni, gi, start, fit))
                base = self.existing_reqs.get(ni, en.requirements)
                if any(not base.has(r.key) or base.get(r.key) != r for r in g.reqs):
                    self.existing_reqs[ni] = _intersect(base, g.reqs)
                remaining[ni] -= fit * np.array(
                    [g.requests.get(n, 0.0) for n in self.engine.resource_dims],
                    dtype=np.float64,
                )
                left -= fit
            g.placed_existing = total - left

    # -- claim-class FFD ------------------------------------------------------

    def _narrow_types(self, types: np.ndarray, usage_q: np.ndarray) -> np.ndarray:
        return types & np.all(self.alloc_q >= usage_q[None, :], axis=1)

    def _fill_classes(self, gi: int, g: _Group, left: int) -> int:
        """Join existing claim classes, emptiest first (scheduler.go:453-457
        sorts in-flight claims by pod count ascending before CanAdd)."""
        for cls in sorted(self.classes, key=lambda c: c.pods_per_claim):
            if left == 0:
                break
            if cls.n_claims == 0:
                continue
            if cls.reqs.compatible(g.reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS) is not None:
                continue
            if Taints(cls.template.spec.taints).tolerates_pod(g.pods[0]) is not None:
                continue
            cand = cls.types & self.feas_free[gi]
            if not cand.any():
                continue
            headroom = self.alloc_q[cand] - cls.usage_q[None, :]
            with np.errstate(divide="ignore"):
                per_type = np.where(
                    g.requests_q[None, :] > 0,
                    headroom // np.maximum(g.requests_q[None, :], 1),
                    np.iinfo(np.int64).max,
                )
            per_type = np.where(np.all(headroom >= 0, axis=1, keepdims=True), per_type, -1)
            k = int(np.max(np.min(per_type, axis=1), initial=-1))
            if k <= 0:
                continue
            if _narrows(cls.reqs, g.reqs):
                self.needs_verify = True
            joint = _intersect(cls.reqs, g.reqs)
            # claims filled to capacity k, then possibly one partial claim
            n_full = min(cls.n_claims, left // k)
            rem = (left - n_full * k) if n_full < cls.n_claims else 0
            took = n_full * k + rem
            if took == 0:
                continue
            for count, n_cl in ((k, n_full), (rem, 1 if rem else 0)):
                if n_cl == 0 or count == 0:
                    continue
                usage = cls.usage_q + count * g.requests_q
                self.classes.append(
                    _ClaimClass(
                        cls.template,
                        joint,
                        self._narrow_types(cand, usage),
                        usage,
                        cls.pods_per_claim + count,
                        n_cl,
                        cls.members + [(gi, count)],
                    )
                )
            cls.n_claims -= n_full + (1 if rem else 0)
            left -= took
        return left

    def _open_claims(self, gi: int, g: _Group, left: int) -> int:
        """Open new claims from the first feasible template in weight order
        (scheduler.go:478-556 earliest-index-wins)."""
        s = self.s
        for ti, nct in enumerate(s.nodeclaim_templates):
            if Taints(nct.spec.taints).tolerates_pod(g.pods[0]) is not None:
                continue
            if nct.requirements.compatible(g.reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS) is not None:
                continue
            mask = self.tmpl_types[ti] & self.feas_free[gi]
            remaining_limits = self.remaining_resources.get(nct.nodepool_name)
            if remaining_limits:
                mask = mask & self._limits_mask(nct, remaining_limits)
            if not mask.any():
                continue
            base = self.tmpl_overhead_q[ti] + g.requests_q
            headroom = self.alloc_q[mask] - self.tmpl_overhead_q[ti][None, :]
            with np.errstate(divide="ignore"):
                per_type = np.where(
                    g.requests_q[None, :] > 0,
                    headroom // np.maximum(g.requests_q[None, :], 1),
                    np.iinfo(np.int64).max,
                )
            per_type = np.where(np.all(headroom >= 0, axis=1, keepdims=True), per_type, 0)
            k = int(np.max(np.min(per_type, axis=1), initial=0))
            if k <= 0:
                continue
            if _narrows(nct.requirements, g.reqs):
                self.needs_verify = True
            joint = _intersect(nct.requirements, g.reqs)
            n_full, rem = divmod(left, k)
            for count, n_cl in ((k, n_full), (rem, 1 if rem else 0)):
                if n_cl == 0 or count == 0:
                    continue
                usage = self.tmpl_overhead_q[ti] + count * g.requests_q
                self.classes.append(
                    _ClaimClass(
                        nct,
                        joint,
                        self._narrow_types(mask, usage),
                        usage,
                        count,
                        n_cl,
                        [(gi, count)],
                    )
                )
                self._subtract_max(nct, mask, n_cl)
            return 0
        for pod in g.pods[len(g.pods) - left :]:
            self.pod_errors[pod] = ValueError(
                "all nodepools were incompatible or had no feasible instance types"
            )
        return 0

    def _limits_mask(self, nct, remaining: dict) -> np.ndarray:
        mask = np.ones(self.engine.num_instances, dtype=bool)
        for name, limit in remaining.items():
            dim = self.engine.resource_dims.get(name)
            if dim is None:
                continue
            limit_q = feas.quantize_resources(
                np.array([limit], dtype=np.float64), ceil=False, scales=self.scales[dim : dim + 1]
            )[0]
            mask &= self.alloc_q[:, dim] <= limit_q
        return mask

    def _subtract_max(self, nct, mask: np.ndarray, n_claims: int) -> None:
        """Pessimistic nodepool-limit tracking: subtract the max resources
        over the claim's options per claim (scheduler.go:744-765)."""
        remaining = self.remaining_resources.get(nct.nodepool_name)
        if not remaining:
            return
        idxs = np.nonzero(mask)[0]
        maxes: dict[str, float] = {}
        for i in idxs:
            for name, v in self.engine.instance_types[i].allocatable().items():
                if v > maxes.get(name, 0.0):
                    maxes[name] = v
        scaled = {k: v * n_claims for k, v in maxes.items()}
        self.remaining_resources[nct.nodepool_name] = res.subtract(remaining, scaled)

    # -- final verification ---------------------------------------------------

    def _verify(self) -> bool:
        """Re-filter every class against its ACCUMULATED requirements in one
        batched device call. Returns False (→ host fallback) if any class's
        type set shrinks below what the packing assumed. Skipped when no two
        sources ever constrained the same key differently — then per-source
        intersection IS the joint feasibility and the round trip is wasted."""
        if not self.classes or not self.needs_verify:
            return True
        e = self.engine
        row_sets = [e.rows_for(c.reqs) for c in self.classes]
        key_present = e.key_presence([c.reqs for c in self.classes])
        requests = np.zeros((len(self.classes), self.D), dtype=np.float32)
        fz = e.feasibility(row_sets, requests, key_present)
        joint_ok = fz.compat & fz.has_offering  # [C, I]
        for ci, cls in enumerate(self.classes):
            narrowed = cls.types & joint_ok[ci]
            fits = self._narrow_types(narrowed, cls.usage_q)
            if not fits.any():
                return False
            cls.types = fits
        return True

    # -- output ---------------------------------------------------------------

    def _emit(self) -> None:
        """Materialize scheduler state: existing-node fills, nodepool limit
        tracking, and host SchedNodeClaim objects (one per claim)."""
        import copy as _copy

        from karpenter_tpu.scheduler.nodeclaim import NodeClaim as SchedNodeClaim

        s = self.s
        for ni, gi, start, count in self.existing_fills:
            en = s.existing_nodes[ni]
            g = self.groups[gi]
            take = g.pods[start : start + count]
            en.pods.extend(take)
            en.remaining_resources = res.subtract(
                en.remaining_resources, {k: v * count for k, v in g.requests.items()}
            )
        for ni, reqs in self.existing_reqs.items():
            s.existing_nodes[ni].requirements = reqs
        s.remaining_resources.update(self.remaining_resources)
        # per-group cursors for handing out pod slices; existing-node fills
        # consumed the head of each group's pod list
        cursors = [g.placed_existing for g in self.groups]
        for cls in self.classes:
            if cls.n_claims <= 0:
                continue
            options = []
            for it in cls.template.instance_type_options:
                idx = self.type_index.get(id(it))
                if idx is None:
                    idx = self._name_index.get(it.name)
                if idx is not None and cls.types[idx]:
                    options.append(it)
            for _ in range(cls.n_claims):
                nc = SchedNodeClaim(
                    cls.template,
                    s.topology,
                    s.daemon_overhead[cls.template],
                    _copy.deepcopy(s.daemon_hostports[cls.template]),
                    options,
                    s.reservation_manager,
                    s.reserved_offering_mode,
                    s.reserved_capacity_enabled,
                    engine=s.engine,
                )
                reqs = Requirements(*cls.reqs.values())
                reqs.add(*nc.requirements.values())  # keeps hostname placeholder
                nc.requirements = reqs
                requests = dict(s.daemon_overhead[cls.template])
                for gi, count in cls.members:
                    g = self.groups[gi]
                    take = g.pods[cursors[gi] : cursors[gi] + count]
                    cursors[gi] += count
                    nc.pods.extend(take)
                    requests = res.merge(
                        requests, {k: v * count for k, v in g.requests.items()}
                    )
                nc.requests = requests
                s.new_node_claims.append(nc)


def solve_device(scheduler, pods: Sequence[Pod]):
    """Run the device FFD; returns Results, or None → caller uses the host
    loop (either ineligible or the final verification found the per-group
    feasibility intersection was looser than the joint one)."""
    global DEVICE_SOLVES, DEVICE_FALLBACKS
    from karpenter_tpu.scheduler.scheduler import Results

    if not eligible(scheduler, pods):
        DEVICE_FALLBACKS += 1
        return None
    solve = _DeviceSolve(scheduler, pods)
    if not solve._encode():
        DEVICE_FALLBACKS += 1
        return None
    solve._template_masks()
    solve._fill_existing()
    for gi, g in enumerate(solve.groups):
        left = len(g.pods) - g.placed_existing
        if left == 0:
            continue
        left = solve._fill_classes(gi, g, left)
        if left > 0:
            solve._open_claims(gi, g, left)
    if not solve._verify():
        DEVICE_FALLBACKS += 1
        return None
    solve._emit()
    DEVICE_SOLVES += 1
    for nc in scheduler.new_node_claims:
        nc.finalize_scheduling()
    return Results(
        new_node_claims=scheduler.new_node_claims,
        existing_nodes=scheduler.existing_nodes,
        pod_errors=solve.pod_errors,
    )
