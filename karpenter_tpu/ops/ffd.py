"""Device-accelerated first-fit-decreasing: the TPU fast path the
Provisioner actually executes, with EXACT host-decision parity.

The reference's solver is a per-pod loop — Pop → try existing nodes →
try in-flight claims (emptiest first) → open a new claim from the weighted
templates (scheduler.go:346-401, :451-557). Its hottest inner op is
`filterInstanceTypesByRequirements` over every instance type
(nodeclaim.go:373-441). This module reshapes that work TPU-first while
reproducing the host loop's decisions bit-for-bit:

1. Pods collapse into groups of identical spec shapes; pod data (requirement
   parsing) runs ONCE per distinct shape instead of once per pod.
2. ONE batched device call evaluates the joint (template x group)
   requirement feasibility over the catalog — membership matmuls on the MXU
   (CatalogEngine.feasibility). Set compatibility is a per-requirement AND
   (requirements.go:248-268), so AND-ing the cached row vectors of the TRUE
   joint requirement set (whose rows are the per-key intersections produced
   by Requirements.add) is bit-identical to the host filter — including the
   per-offering cross-key conjunction the pairwise masks miss.
3. The packing loop is an EXACT simulation of the host queue: pods are
   processed in the host's sort order (cpu desc, mem desc, timestamp, uid;
   queue.go:72-108), each pod tries existing nodes in order, then in-flight
   claims in the host's emptiest-first *stable-sort* order, then the
   weighted templates. Every rejection reason is monotone (requirements
   only narrow, usage only grows, limits only shrink), so rejections are
   cached permanently and steady-state placements cost O(1) per pod:
   lazy-keyed heaps model the stable sort, per-(claim, group) capacity
   schedules replace the per-pod filter.
4. Higher-order joint requirement sets (a claim accumulating several
   narrowing groups) are evaluated host-side from the engine's cached row
   matrices — exact, no device round-trip on the sequential path.

Eligibility is checked first (`eligible`). Every scheduling construct runs
on the device path: topology, host ports, volumes, hostname pins, minValues
in BOTH policies (Strict's per-join diversity gate; BestEffort's open-time
relaxation into per-claim specs), reserved capacity in BOTH offering modes
(fallback bookkeeping per join; strict's scan-aborting errors on the
all-volatile topo driver), and PreferNoSchedule relaxation. The host loop
remains the semantics oracle. Topology-engaged, host-port/volume, hostname,
PreferNoSchedule, and strict-reserved solves run the topo-aware driver
(ops/ffd_topo.py).
"""

from __future__ import annotations

import heapq
import itertools
import time
from typing import TYPE_CHECKING, Optional, Sequence

import numpy as np

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Pod
from karpenter_tpu.metrics import global_registry
from karpenter_tpu.observability import explain as explmod
from karpenter_tpu.scheduler.nodeclaim import InstanceTypeFilterError
from karpenter_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN_LABELS,
    Requirement,
    Requirements,
)
from karpenter_tpu.scheduling.hostportusage import HostPortUsage
from karpenter_tpu.scheduling.requirements import Operator
from karpenter_tpu.scheduling.taints import Taints
from karpenter_tpu.utils import resources as res

if TYPE_CHECKING:
    from karpenter_tpu.ops.catalog import CatalogEngine

# Below this batch size the host per-pod loop is comfortably fast and covers
# every feature; the device path's fixed costs don't pay off.
DEVICE_MIN_PODS = 64
# Existing-node joins run through host requirement algebra per (node, group)
# pair with monotone scan pointers, so large clusters stay O(nodes + pods);
# the cap is a safety valve for pathological node counts. 4096 keeps the
# 1k-candidate consolidation simulations (7 binary-search rounds over ~1000
# surviving nodes each) on the fast path.
DEVICE_MAX_EXISTING = 4096

# Observability: how often the fast path ran vs fell back. Mirrored into the
# metrics registry so operators can alert on fallback storms.
DEVICE_SOLVES = 0
DEVICE_FALLBACKS = 0
_SOLVES_CTR = global_registry.counter(
    "karpenter_scheduler_device_solves_total",
    "scheduling solves served by the device fast path",
)
_FALLBACKS_CTR = global_registry.counter(
    "karpenter_scheduler_device_fallbacks_total",
    "scheduling solves that fell back to the host loop",
)
# Joint-mask device sweeps: each increment is one batched [P, I] feasibility
# cube dispatch over fresh joint requirement sets. solverd's coalescer uses
# this to prove concurrent solves sharing an engine merged into ONE batch.
JOINT_SWEEPS = 0
_JOINT_SWEEPS_CTR = global_registry.counter(
    "karpenter_solver_joint_sweeps_total",
    "batched joint-requirement feasibility sweeps dispatched to the device path",
)
# Cache-hit attribution for the engine-shared solver caches: the solverd
# solve span snapshots these around each solve so slow solves can be
# attributed to cold caches vs device work. Process-history state — span
# code records the deltas as VOLATILE attrs (excluded from deterministic
# span digests; a warm second run legitimately hits where a cold first run
# missed).
JOINT_CACHE_HITS = 0
JOINT_CACHE_MISSES = 0
PACK_CACHE_HITS = 0
PACK_CACHE_MISSES = 0


def solver_cache_counters() -> dict:
    """Snapshot of the solver's cumulative cache/dispatch counters (delta
    two snapshots to attribute one solve). Includes the topology count-gate
    counters (ops/topo_counts.py) so solverd solve spans can attribute a
    slow topo solve to oracle fallbacks / tensor resyncs the same way they
    attribute cold joint/pack caches."""
    from karpenter_tpu.ops import topo_counts

    out = {
        "joint_cache_hits": JOINT_CACHE_HITS,
        "joint_cache_misses": JOINT_CACHE_MISSES,
        "pack_cache_hits": PACK_CACHE_HITS,
        "pack_cache_misses": PACK_CACHE_MISSES,
        "joint_sweeps": JOINT_SWEEPS,
        "device_solves": DEVICE_SOLVES,
        "device_fallbacks": DEVICE_FALLBACKS,
    }
    out.update(topo_counts.gate_counters())
    # fused one-dispatch scan accounting (solves + decline taxonomy); lazy
    # import keeps the ffd<->fused module cycle one-directional at import
    from karpenter_tpu.ops import fused as _fused

    out.update(_fused.fused_counters())
    # incremental-solve residency accounting (ops/delta.py): warm/cold
    # passes, bytes re-encoded, scan resume outcomes, self-check verdicts —
    # snapshot-and-delta attributes one solve's delta behavior the same way
    from karpenter_tpu.ops import delta as _delta

    out.update(_delta.delta_counters())
    return out


# /metrics mirror of solver_cache_counters: the module-global ints above are
# span-visible only (volatile solve attrs); operators alerting on e.g. the
# affinity self-seed host-delegation path regressing need topo_oracle_calls
# as a scrapeable counter. publish_cache_counters() diffs the cumulative
# snapshot against the last published values and inc()s the delta — called
# after every solverd batch (solverd/service.run_pending), so the series
# lag a batch at most.
_CACHE_EVENTS_CTR = global_registry.counter(
    "karpenter_solver_cache_events_total",
    "cumulative solver cache/dispatch/delegation events "
    "(ffd.solver_cache_counters: joint/pack cache hits+misses, joint "
    "sweeps, device solves/fallbacks, topo gate evals/refreshes, "
    "topo_oracle_calls, tensor resyncs)",
    labels=["event"],
)
_published_cache_counters: dict[str, int] = {}


def publish_cache_counters() -> dict:
    """Mirror the cumulative solver cache counters onto /metrics; returns
    the snapshot it published."""
    snap = solver_cache_counters()
    for name, value in snap.items():
        prev = _published_cache_counters.get(name, 0)
        if value > prev:
            _CACHE_EVENTS_CTR.inc({"event": name}, value - prev)
            _published_cache_counters[name] = value
    return snap


# Tests set this to make simulation bugs fail loudly instead of silently
# falling back to the host loop.
STRICT = False

_EPS = 1e-9
_BIG = np.int64(2**31)

_placeholder_counter = itertools.count(1)

# process-global shape-signature interning: the full _raw_sig tuple hashes in
# microseconds at 50k pods, so pods carry a small int instead and per-solve
# group lookup is an int-keyed dict hit. The dict is cleared at a cap to
# bound memory on high shape diversity; ids come from a never-reset counter,
# so a re-interned shape gets a fresh id and its old/new pods merely split
# into two value-identical groups (dedup cost, never a correctness issue).
_SIG_IDS: dict[tuple, int] = {}
_SIG_NEXT = itertools.count()
_SIG_CAP = 200_000
# engine-shared cross-solve caches (joint requirement masks, family
# transitions) share one cap; see set_memory_budget
_ENGINE_CACHE_CAP = 100_000


def _evict_lru(cache: dict, cap: int) -> None:
    """Trim an engine-shared cache to ~90% of `cap`, dropping the LEAST
    recently touched entries. Python dicts iterate in insertion order and
    every cache hit reinserts its entry at the tail, so iteration order IS
    recency order — the head is the coldest entry. Unlike the previous
    wholesale clear(), hitting the cap costs only the cold tail, never the
    warm working set."""
    if len(cache) <= cap:
        return
    drop = len(cache) - (cap - cap // 10)
    for k in list(itertools.islice(iter(cache), drop)):
        del cache[k]


def set_memory_budget(limit_mib: int) -> None:
    """Bound the solver's unbounded-by-default caches to a memory budget.

    The reference wires --memory-limit into GOMEMLIMIT at 90%
    (pkg/operator/operator.go:115-118) so the GC keeps the process under
    its cgroup. Python has no GC ceiling; the operator's only unbounded
    memory consumers are these interning/memo caches, so the budget
    scales their clear-at caps instead. Sizing: a signature tuple runs
    ~300B, a joint-mask entry ~1KiB — defaults (200k/100k) assume ~160MiB
    of cache headroom; the caps scale linearly below that and never rise
    above the defaults."""
    global _SIG_CAP, _ENGINE_CACHE_CAP
    if limit_mib is None or limit_mib <= 0:
        _SIG_CAP, _ENGINE_CACHE_CAP = 200_000, 100_000
        return
    scale = min(1.0, limit_mib / 160.0)
    _SIG_CAP = max(1_000, int(200_000 * scale))
    _ENGINE_CACHE_CAP = max(1_000, int(100_000 * scale))


# -- eligibility -------------------------------------------------------------


def eligible(scheduler, pods: Sequence[Pod]) -> bool:
    """True when the device path can reproduce host semantics for this solve
    (solve-level gates; per-pod gates run once per GROUP during grouping).
    Topology-engaged solves are additionally gated by ffd_topo.supported()
    inside solve_device — spread-only solves run the topo-aware driver."""
    if scheduler.engine is None:
        return False
    if len(pods) < DEVICE_MIN_PODS:
        # DEVICE_MIN_PODS is a dispatch-RTT heuristic, not a correctness
        # gate. An operator that forced the fused path AND incremental
        # delta solves has opted into device-resident state — tiny churn
        # batches are exactly the traffic that mode exists for, and
        # bouncing them to the host walk would both skip the warm
        # scan-resume and force a host resync of the count tensors.
        from karpenter_tpu.ops import delta as delta_mod
        from karpenter_tpu.ops import fused as fused_mod

        if not (delta_mod.delta_enabled() and fused_mod.FUSED_MODE == "on"):
            return False
    if len(scheduler.existing_nodes) > DEVICE_MAX_EXISTING:
        return False
    # PreferNoSchedule pools extend the relax ladder with the wildcard
    # toleration rung (preferences.go:133-145): every pod is potentially
    # relaxable, so those solves route straight to the topo driver (which
    # relaxes exactly like the host) — see solve_device.
    # Reserved capacity is device-supported in BOTH modes. Fallback (the
    # default): bookkeeping runs on every join exactly like the host's
    # can_add→Add cycle and never REJECTS a candidate, so the monotone
    # machinery stays sound. Strict: reservation exhaustion raises
    # scan-aborting ReservedOfferingErrors (scheduler.go:519,574
    # short-circuits) — non-monotone, so those solves route to the topo
    # driver with every shape volatile (see solve_device/_prepare_templates).
    # The catalog scan is cached on the (immutable) engine catalog.
    if scheduler.reserved_capacity_enabled:
        has_reserved = getattr(scheduler.engine, "_kt_has_reserved", None)
        if has_reserved is None:
            has_reserved = any(
                o.capacity_type == wk.CAPACITY_TYPE_RESERVED
                for it in scheduler.engine.instance_types
                for o in it.offerings
            )
            scheduler.engine._kt_has_reserved = has_reserved
    dims = scheduler.engine.resource_dims
    for nct in scheduler.nodeclaim_templates:
        # minValues is fully supported in BOTH policies. Strict: monotone
        # (narrowing only shrinks the distinct-value count, so rejections
        # are permanent). BestEffort: relaxation happens once per claim at
        # OPEN (nodeclaim.go:425-436) into per-claim specs — interned family
        # rows are never mutated, and joins gate on the relaxed values just
        # like the host's max-merged claim requirements.
        # hostname-constrained templates would break family sharing (the
        # canonical family Requirements are hostname-free)
        if nct.requirements.has(wk.LABEL_HOSTNAME):
            return False
        if any(k not in dims for k in scheduler.daemon_overhead[nct]):
            return False
    return True


def _strict_reserved(scheduler) -> bool:
    """One predicate for strict-mode reserved routing — shared by
    solve_device's driver selection and _DeviceSolve.strict_res so the two
    can never disagree."""
    if not (
        scheduler.reserved_capacity_enabled
        and getattr(scheduler.engine, "_kt_has_reserved", False)
    ):
        return False
    from karpenter_tpu.scheduler.nodeclaim import RESERVED_OFFERING_MODE_STRICT

    return scheduler.reserved_offering_mode == RESERVED_OFFERING_MODE_STRICT


def _has_pod_affinity_terms(aff) -> bool:
    """Termless PodAffinity/PodAntiAffinity objects are inert — they create
    no topology groups and the relax ladder skips them."""
    pa = aff.pod_affinity
    if pa is not None and (pa.required or pa.preferred):
        return True
    panti = aff.pod_anti_affinity
    if panti is not None and (panti.required or panti.preferred):
        return True
    return False


def _group_eligible(pod: Pod) -> bool:
    """Per-shape gates, checked once per distinct pod shape."""
    spec = pod.spec
    aff = spec.affinity
    if aff is not None:
        if _has_pod_affinity_terms(aff):
            return False
        na = aff.node_affinity
        if na is not None and (na.preferred or len(na.required) > 1):
            return False
    if spec.topology_spread_constraints:
        return False
    if any(c.ports for c in list(spec.containers) + list(spec.init_containers)):
        return False
    if getattr(spec, "volumes", None):
        return False
    return True


# -- grouping ----------------------------------------------------------------


class _Group:
    __slots__ = (
        "reqs", "strict_reqs", "requests", "req_f", "div_dims", "div_req",
        "tier", "fit_floor", "sort_cpu", "sort_mem", "n_pods", "rowset",
        "has_hostname", "req_list", "floor_list",
    )

    def __init__(self, data, dims: dict):
        self.reqs: Requirements = data.requirements
        self.strict_reqs: Requirements = data.strict_requirements
        self.requests: dict = data.requests
        self.req_f = np.zeros(len(dims), dtype=np.float64)
        for name, v in data.requests.items():
            self.req_f[dims[name]] = v
        self.div_dims = np.nonzero(self.req_f > 0)[0]
        self.div_req = self.req_f[self.div_dims]
        # Resource tier: groups with IDENTICAL request vectors share claim
        # capacity schedules (fits depends only on the vector, not the group).
        self.tier = self.req_f.tobytes()
        # Fit threshold: usage + req <= alloc + eps  ⟺  rem >= req - eps
        self.fit_floor = self.req_f - 1e-9
        # Python-scalar mirrors for the deferred-claim fast path (the
        # per-join admission/commit run scalar loops over D dims — cheaper
        # than numpy dispatch at D ~ 8)
        self.req_list = self.req_f.tolist()
        self.floor_list = self.fit_floor.tolist()
        self.sort_cpu = data.requests.get(wk.RESOURCE_CPU, 0.0)
        self.sort_mem = data.requests.get(wk.RESOURCE_MEMORY, 0.0)
        self.n_pods = 0
        self.rowset: frozenset = frozenset()  # filled once the engine interns
        self.has_hostname = any(r.key == wk.LABEL_HOSTNAME for r in data.requirements)


def _raw_sig(pod: Pod) -> tuple:
    """Cheap value-signature over every spec field that can influence an
    ELIGIBLE pod's scheduling: selector, single required affinity term,
    container resources, tolerations, and the eligibility-gate fields
    themselves (so an ineligible pod can never hide inside an eligible
    group). Dict items are taken in insertion order — two value-equal specs
    built in different key orders merely split into two identical groups,
    which only costs dedup, never correctness. Runs once per pod."""
    spec = pod.spec
    containers = spec.containers
    # fast path: the overwhelmingly common single-container plain pod
    if (
        spec.affinity is None
        and not spec.topology_spread_constraints
        and not spec.tolerations
        and not spec.init_containers
        and not spec.overhead
        and not getattr(spec, "volumes", None)
        and len(containers) == 1
    ):
        c = containers[0]
        return (
            tuple(spec.node_selector.items()) if spec.node_selector else (),
            tuple(c.requests.items()),
            tuple(c.limits.items()) if c.limits else (),
            len(c.ports),
            c.restart_policy,
        )
    aff = spec.affinity
    aff_sig: tuple = ()
    gates = 1
    if aff is not None:
        # non-empty only: must mirror _group_eligible so a termed pod can
        # never share a signature with an eligible termless one
        if _has_pod_affinity_terms(aff):
            gates |= 2
        na = aff.node_affinity
        if na is not None:
            if na.preferred:
                gates |= 4
            aff_sig = tuple(
                tuple(
                    (e["key"], e["operator"], tuple(e.get("values", ())))
                    for e in term.match_expressions
                )
                for term in na.required
            )
    if spec.topology_spread_constraints:
        gates |= 8
    if getattr(spec, "volumes", None):
        gates |= 16
    cont_sig = tuple(
        (
            tuple(c.requests.items()),
            tuple(c.limits.items()) if c.limits else (),
            len(c.ports),
            c.restart_policy,
        )
        for c in containers
    )
    inits = ()
    if spec.init_containers:
        inits = tuple(
            (
                tuple(c.requests.items()),
                tuple(c.limits.items()) if c.limits else (),
                c.restart_policy,
            )
            for c in spec.init_containers
        )
    return (
        tuple(spec.node_selector.items()) if spec.node_selector else (),
        aff_sig,
        gates,
        cont_sig,
        inits,
        tuple(spec.overhead.items()) if spec.overhead else (),
        tuple((t.key, t.operator, t.value, t.effect) for t in spec.tolerations)
        if spec.tolerations
        else (),
    )


# -- simulation structures ---------------------------------------------------


class _Claim:
    """An in-flight NodeClaim under simulation.

    Fits-narrowing TELESCOPES: because usage only grows, the host's per-join
    option filter satisfies types_k = types_0 ∧ fits(U_k). The claim keeps
    the remaining headroom `rem = allocatable − usage` over exactly the
    UNIQUE allocatable vectors that still fit the current usage — rows that
    stop fitting are pruned permanently, so every join is a handful of
    small-array ops; the emitted option set is type_mask ∧ surviving rows.

    Requirement state is an interned FAMILY id: claims sharing a requirement
    row-set share one id, one canonical (hostname-free) Requirements object,
    and one memoized join-transition table — the expensive requirement
    algebra runs once per (family, group), not once per (claim, group)."""

    __slots__ = (
        "ti", "fam", "hostname", "type_mask", "u_ids", "rem", "count", "rank",
        "members", "group_counts", "gdrop", "gknown", "reserved",
        "min_specs", "min_relaxed", "hn_epoch", "defer",
    )

    def __init__(self, ti, fam, hostname, type_mask, u_ids, rem, rank):
        self.ti = ti
        self.fam = fam  # interned row-set family id
        self.hostname = hostname  # per-claim placeholder value
        self.type_mask = type_mask  # np bool [I]: requirement-level narrowing
        self.u_ids = u_ids  # np int [M] unique-allocatable row ids
        self.rem = rem  # np float64 [M, D] uniq_alloc - current usage
        self.count = 0
        self.rank = rank
        self.members: list[Pod] = []
        self.group_counts: dict[int, int] = {}  # TOTAL pods per group
        self.gdrop: set[int] = set()  # groups permanently rejected
        # Groups whose requirements are subsumed by the claim's (adding them
        # is a no-op). Subsumption survives further narrowing, so membership
        # is permanent.
        self.gknown: set[int] = set()
        # reserved offerings currently held (nodeclaim.go:166-205), refreshed
        # on every successful join like the host's can_add→Add cycle
        self.reserved: list = []
        # minValues specs governing this claim's joins. Strict: the
        # template's. BestEffort: relaxed AT OPEN to the achievable distinct
        # count (nodeclaim.go:425-436) — fixed thereafter, exactly like the
        # host claim whose relaxed requirement min_values max-merge through
        # every later join.
        self.min_specs: list[tuple[str, int]] = []
        self.min_relaxed = False
        # hostname-register epoch (topo driver): the epoch of the hostname
        # topology-group set this claim's hostname was last registered into.
        # Registration is idempotent, so each (claim, group-set epoch) pays
        # exactly one pass over the hostname groups instead of one per join.
        self.hn_epoch = -1
        # Deferred row-pruning state (topo driver fast path), or None.
        # (pareto_rows, extra): `pareto_rows` are the Pareto-maximal rows of
        # the OPEN-time headroom matrix as Python lists; `extra` accumulates
        # the requests joined since open. Row pruning telescopes — a row
        # survives all joins iff alloc >= final usage - eps per dim — so
        # admission is a pareto check against (row - extra) and the full
        # rem/u_ids narrowing is materialized only when a slow path, a
        # minValues/reserved gate, or emit actually reads the rows
        # (_DeviceSolve._materialize).
        self.defer = None


class _Node:
    """Existing-node wrapper; mutations are committed to the scheduler's
    ExistingNode objects only at emit."""

    __slots__ = (
        "en", "reqs", "remaining", "version", "usage_ver", "joined",
        "gtol", "gcompat", "gcap",
    )

    def __init__(self, en):
        self.en = en
        self.reqs = en.requirements
        self.remaining = dict(en.remaining_resources)
        self.version = 0
        self.usage_ver = 0
        self.joined: list[Pod] = []
        self.gtol: dict[int, bool] = {}
        self.gcompat: dict[int, tuple[int, bool]] = {}  # gi -> (version, ok)
        self.gcap: dict[int, tuple[int, int]] = {}  # gi -> (usage_ver, k_left)


class _LazyNodes:
    """Sequence facade over the scheduler's ExistingNodes that materializes
    _Node wrappers on first touch. The monotone FFD scan (_try_nodes) only
    ever reads a prefix of the node order — consolidation simulations pack
    a few hundred pods into the first handful of nodes — so building all
    ~1k wrappers up front was the single largest steady-state solve cost at
    frontier scale. Full iteration (the topo driver's volatile scans, abort
    snapshots) materializes everything, preserving exact semantics;
    `materialized()` exposes only touched wrappers for emit, where an
    untouched node is by construction join-free."""

    __slots__ = ("_ens", "_built")

    def __init__(self, existing_nodes):
        self._ens = existing_nodes
        self._built: list = [None] * len(existing_nodes)

    def __len__(self) -> int:
        return len(self._built)

    def __bool__(self) -> bool:
        return bool(self._built)

    def __getitem__(self, i: int) -> "_Node":
        nd = self._built[i]
        if nd is None:
            nd = self._built[i] = _Node(self._ens[i])
        return nd

    def __iter__(self):
        for i in range(len(self._built)):
            yield self[i]

    def materialized(self):
        return (nd for nd in self._built if nd is not None)


class _Fallback(Exception):
    """Internal: abort the device solve and use the host loop."""


class _IneligibleShape(_Fallback):
    """A pod shape the current driver declines. From the plain driver this
    triggers a retry on the topo driver (whose relax ladder handles
    preferred/multi-term node affinity); from the topo driver it falls
    back to the host loop."""


class _NativeDriver:
    """Drives the C steady-state kernel (ops/_native/ffd_kernel.cc).

    The kernel owns the queue, per-group heaps, and claim headroom state;
    this driver answers its four up-calls — taint tolerance, family-join
    transitions, new-claim openings, existing-node joins — using the same
    _DeviceSolve methods the Python loop uses, so both drivers share one
    semantics implementation for everything that isn't a hot loop."""

    def __init__(self, solve: "_DeviceSolve", pods_sorted: list, gi_arr, timeout):
        from karpenter_tpu.ops import native as nat

        self.nat = nat
        self.lib = nat.get_lib()
        self.s = solve
        self.pods = pods_sorted
        s = solve
        G, D = len(s.groups), s.D
        self.W = max(1, (s.I + 63) // 64)
        g_req = (
            np.ascontiguousarray(np.stack([g.req_f for g in s.groups]))
            if s.groups
            else np.zeros((0, D))
        )
        g_fit = (
            np.ascontiguousarray(np.stack([g.fit_floor for g in s.groups]))
            if s.groups
            else np.zeros((0, D))
        )
        utype = np.zeros((s.U, self.W), dtype=np.uint64)
        for u in range(s.U):
            utype[u] = self._pack(s.uid_of_type == u)
        utype = np.ascontiguousarray(utype)
        # nonzero request dims per group: the C fit/subtract loops touch
        # only these (zero dims provably always pass)
        g_ndim = np.zeros(G, dtype=np.int32)
        g_didx = np.zeros((G, D), dtype=np.int32)
        for k, g in enumerate(s.groups):
            g_ndim[k] = len(g.div_dims)
            g_didx[k, : len(g.div_dims)] = g.div_dims
        self.claim_meta: list[str] = []  # hostname per claim index
        self.err_by_idx: dict[int, Exception] = {}
        self.timeout_idx: set[int] = set()
        self._pack_cache: dict[tuple[bytes, bytes], tuple] = {}
        ctx = self.lib.kt_new(
            len(self.pods),
            G,
            D,
            s.U,
            self.W,
            len(s.s.nodeclaim_templates),
            gi_arr.ctypes.data_as(nat.p_i32),
            g_req.ctypes.data_as(nat.p_f64),
            g_fit.ctypes.data_as(nat.p_f64),
            g_ndim.ctypes.data_as(nat.p_i32),
            g_didx.ctypes.data_as(nat.p_i32),
            utype.ctypes.data_as(nat.p_u64),
            1 if s.nodes else 0,
            -1.0 if timeout is None else float(timeout),
        )
        if not ctx:
            raise _Fallback("native context allocation failed")
        self.ctx = ctx

    def _pack(self, mask: np.ndarray) -> np.ndarray:
        b = np.packbits(np.ascontiguousarray(mask), bitorder="little")
        out = np.zeros(self.W * 8, dtype=np.uint8)
        out[: b.size] = b
        return out.view(np.uint64)

    def add_claim(self, ti, fam, hostname, pod, gi, candidate, u_ids, rem, reusable):
        # called from _open_claim while resolving ACT_NEED_NEW_CLAIM; the
        # opening pod is the one the kernel just handed us. For open_cache-
        # shared candidate arrays (reusable), the packed mask and int32 u_ids
        # are cached per array identity: openings for the same (template,
        # group) reuse one encoding. One-shot arrays (limits in play) are
        # encoded inline — caching them could never hit.
        nat = self.nat
        self.claim_meta.append(hostname)
        if reusable:
            # value fingerprint, not id(): object ids recycle after GC, so a
            # recycled candidate array could hit a stale entry. The fingerprint
            # must cover BOTH arrays — two (template, group) openings can share
            # a candidate mask yet differ in fitting u_ids. Value keying also
            # lets value-identical openings share one encoding.
            global PACK_CACHE_HITS, PACK_CACHE_MISSES
            cache_key = (candidate.tobytes(), np.ascontiguousarray(u_ids).tobytes())
            cached = self._pack_cache.get(cache_key)
            if cached is None:
                PACK_CACHE_MISSES += 1
                mask = self._pack(candidate)
                u32 = np.ascontiguousarray(u_ids, dtype=np.int32)
                # pre-cast the stable pointers: openings for the same
                # (template, group) repeat thousands of times per pass and
                # ctypes casts are measurable at that rate; the arrays are
                # held in the tuple so their buffers can't move or recycle
                cached = (
                    mask.ctypes.data_as(nat.p_u64),
                    u32.ctypes.data_as(nat.p_i32),
                    len(u32),
                    mask,
                    u32,
                )
                self._pack_cache[cache_key] = cached
            else:
                PACK_CACHE_HITS += 1
            mask_ptr, u32_ptr, n_u = cached[0], cached[1], cached[2]
        else:
            mask = self._pack(candidate)
            u32 = np.ascontiguousarray(u_ids, dtype=np.int32)
            mask_ptr = mask.ctypes.data_as(nat.p_u64)
            u32_ptr = u32.ctypes.data_as(nat.p_i32)
            n_u = len(u32)
        remc = np.ascontiguousarray(rem, dtype=np.float64)
        self.lib.kt_add_claim(
            self.ctx,
            ti,
            fam,
            self._cur_pod_idx,
            gi,
            mask_ptr,
            u32_ptr,
            remc.ctypes.data_as(nat.p_f64),
            n_u,
        )

    def drive(self) -> None:
        nat, lib, ctx, s = self.nat, self.lib, self.ctx, self.s
        out = (nat.i64 * 8)()
        templates = s.s.nodeclaim_templates
        while True:
            act = lib.kt_run(ctx, out)
            if act == nat.ACT_DONE:
                break
            if act == nat.ACT_TIMEOUT:
                s.timed_out = True
                head = int(out[0])
                qlen = int(lib.kt_queue_len(ctx))
                tail = np.zeros(max(qlen - head, 0), dtype=np.int32)
                if tail.size:
                    lib.kt_queue_tail(ctx, head, tail.ctypes.data_as(nat.p_i32))
                for idx in tail.tolist():
                    self.timeout_idx.add(idx)
                    self.err_by_idx.setdefault(
                        idx, TimeoutError("scheduling simulation timed out")
                    )
                break
            if act == nat.ACT_NEED_TOL:
                pidx, gi, _ci, ti = int(out[0]), int(out[1]), int(out[2]), int(out[3])
                tol = Taints(templates[ti].spec.taints).tolerates_pod(
                    self.pods[pidx]
                ) is None
                s.tg_tol[(ti, gi)] = tol
                lib.kt_set_tol(ctx, ti, gi, 1 if tol else 0)
                continue
            if act == nat.ACT_NEED_JOIN:
                _pidx, gi, _ci, fam = int(out[0]), int(out[1]), int(out[2]), int(out[3])
                ent = s.fam_join.get((fam, gi))
                if ent is None:
                    ent = s._build_fam_join(fam, gi)
                if ent[0] == s._REJECT:
                    lib.kt_set_join(ctx, fam, gi, nat.JOIN_REJECT, 0, None)
                elif ent[0] == s._SAME:
                    lib.kt_set_join(ctx, fam, gi, nat.JOIN_SAME, 0, None)
                else:
                    mask = self._pack(ent[2])
                    lib.kt_set_join(
                        ctx,
                        fam,
                        gi,
                        nat.JOIN_NARROW,
                        ent[1],
                        mask.ctypes.data_as(nat.p_u64),
                    )
                continue
            if act == nat.ACT_NEED_NEW_CLAIM:
                pidx, gi = int(out[0]), int(out[1])
                pod = self.pods[pidx]
                self._cur_pod_idx = pidx
                if not templates:
                    err: Optional[Exception] = ValueError(
                        "nodepool requirements filtered out all available instance types"
                    )
                else:
                    err = s._new_claim(pod, s.groups[gi], gi)
                if err is None:
                    lib.kt_resolve(ctx, 1)
                else:
                    self.err_by_idx[pidx] = err
                    lib.kt_resolve(ctx, 2)
                continue
            if act == nat.ACT_NEED_NODES:
                pidx, gi = int(out[0]), int(out[1])
                pod = self.pods[pidx]
                placed = s._try_nodes(pod, s.groups[gi], gi)
                if s.nptr[gi] >= len(s.nodes):
                    lib.kt_set_nodes_done(ctx, gi)
                lib.kt_resolve(ctx, 1 if placed else 0)
                continue
            raise _Fallback(f"native kernel returned unknown action {act}")
        self._finish()

    def _finish(self) -> None:
        """Materialize claims and pod errors back into the _DeviceSolve."""
        nat, lib, ctx, s = self.nat, self.lib, self.ctx, self.s
        failed = np.zeros(len(self.pods), dtype=np.uint8)
        if len(self.pods):
            lib.kt_failed(ctx, failed.ctypes.data_as(nat.p_u8))
        for idx, err in self.err_by_idx.items():
            if failed[idx] or idx in self.timeout_idx:
                s.pod_errors[self.pods[idx]] = err
        # bulk export: two calls instead of 2 per claim
        sizes = (nat.i64 * 4)()
        lib.kt_export_sizes(ctx, sizes)
        n, total_u, total_m, total_g = (int(sizes[k]) for k in range(4))
        if n == 0:
            return
        info = np.zeros(n * 6, dtype=np.int64)
        words = np.zeros(n * self.W, dtype=np.uint64)
        u_ids_flat = np.zeros(max(total_u, 1), dtype=np.int32)
        members_flat = np.zeros(max(total_m, 1), dtype=np.int32)
        groups_flat = np.zeros(max(total_g, 1), dtype=np.int32)
        counts_flat = np.zeros(max(total_g, 1), dtype=np.int32)
        lib.kt_export(
            ctx,
            info.ctypes.data_as(nat.p_i64),
            words.ctypes.data_as(nat.p_u64),
            u_ids_flat.ctypes.data_as(nat.p_i32),
            members_flat.ctypes.data_as(nat.p_i32),
            groups_flat.ctypes.data_as(nat.p_i32),
            counts_flat.ctypes.data_as(nat.p_i32),
        )
        info = info.reshape(n, 6)
        all_masks = (
            np.unpackbits(
                words.reshape(n, self.W).view(np.uint8), axis=1, bitorder="little"
            )[:, : s.I]
            .astype(bool)
        )
        ui = mi = gi2 = 0
        for ci in range(n):
            ti, fam, count, M, n_members, n_groups = (int(v) for v in info[ci])
            c = _Claim(
                ti,
                fam,
                self.claim_meta[ci],
                all_masks[ci],
                u_ids_flat[ui : ui + M].astype(np.int64),
                np.zeros((0, s.D)),
                0,
            )
            ui += M
            c.count = count
            c.members = [self.pods[i] for i in members_flat[mi : mi + n_members].tolist()]
            mi += n_members
            c.group_counts = {
                int(g): int(k)
                for g, k in zip(
                    groups_flat[gi2 : gi2 + n_groups].tolist(),
                    counts_flat[gi2 : gi2 + n_groups].tolist(),
                )
            }
            gi2 += n_groups
            s.claims.append(c)

    def close(self) -> None:
        if self.ctx:
            self.lib.kt_free(self.ctx)
            self.ctx = None


class _DeviceSolve:
    def __init__(self, scheduler, pods: Sequence[Pod]):
        self.s = scheduler
        self.engine: "CatalogEngine" = scheduler.engine
        self.pods = pods
        e = self.engine
        self.dims = e.resource_dims
        self.D = len(self.dims)
        self.I = e.num_instances
        self.alloc_f = e.allocatable  # [I, D] float64
        self.cap_f = e.capacity  # [I, D] float64
        # Catalogs repeat allocatable vectors (size families × zones); fit
        # checks collapse to the unique rows, shrinking every claim's
        # headroom matrix ~I/U-fold.
        self.uniq_alloc, self.uid_of_type = np.unique(
            self.alloc_f, axis=0, return_inverse=True
        )
        self.U = self.uniq_alloc.shape[0]
        self.groups: list[_Group] = []
        self.claims: list[_Claim] = []
        self.nodes = _LazyNodes(scheduler.existing_nodes)
        self.seq = 0  # bucket-entry counter for the stable-sort order model
        # joint requirement-set masks: frozenset(row ids) -> (compat, offer).
        # Shared on the ENGINE across solves: steady-state provisioner
        # passes re-derive identical joints, and masks are pure content
        # functions (rows are interned per engine). LRU-bounded: _joint_masks
        # reinserts on every hit, so eviction sheds only cold entries.
        _evict_lru(e.solver_joint_cache, _ENGINE_CACHE_CAP)
        self.joint_cache = e.solver_joint_cache
        # requirement-set families: frozenset(row ids) -> id, plus the
        # canonical hostname-free Requirements per id and the memoized join
        # transitions (family, group) -> reject | same | narrow
        self.fam_ids: dict[frozenset, int] = {}
        self.fam_rows: list[frozenset] = []
        self.fam_reqs: list[Requirements] = []
        self.fam_join: dict[tuple[int, int], tuple] = {}
        self.remaining_resources = {
            name: dict(rl) for name, rl in scheduler.remaining_resources.items()
        }
        self.limits_version = 0
        # per-pool limit-tracking versions: bumped by _subtract_max so the
        # limits mask and claim-opening caches invalidate only for the pool
        # whose remaining budget actually moved (8-pool solves would
        # otherwise recompute every open from scratch)
        self.pool_limits_ver: dict[str, int] = {}
        self._limits_mask_cache: dict[str, tuple[int, np.ndarray]] = {}
        # (ti, pool_ver) -> True (types remain) | the cached exhaustion error
        self._limits_any: dict[tuple[int, int], object] = {}
        # (ti, gi, id(limits_mask)) ->
        # (candidate, row_sel, u_ids, min_specs, min_relaxed, min_msg, mask ref)
        self._limited_open_cache: dict[tuple, tuple] = {}
        # per-group state
        self.gheaps: list[list] = []
        self.gsynced: list[int] = []
        self.nptr: list[int] = []
        # gi -> (limits_version, error, staged explanation funnel or None)
        self.gnewclaim_err: dict[int, tuple[int, Exception, Optional[list]]] = {}
        # (ti, gi) -> memoized LIMITLESS claim-opening data
        # (fam, candidate0, u_ids0, rem0_fit0, min_specs, min_relaxed) or
        # (-1,...) = permanent error; active nodepool limits are applied per
        # open as a type-mask AND over the cached entry (_new_claim)
        self.open_cache: dict[tuple[int, int], tuple] = {}
        self._open_errs: dict[tuple[int, int], Exception] = {}
        # per-(template, group) static caches
        self.tg_tol: dict[tuple[int, int], bool] = {}
        self.tg_compat: dict[tuple[int, int], Optional[tuple]] = {}
        self.pod_errors: dict[Pod, Exception] = {}
        self.timed_out = False
        self._native: Optional[_NativeDriver] = None
        # deferred-claim machinery (enabled by the topo driver when no
        # per-join row reads are needed; see _Claim.defer)
        self._defer_ok = False
        self._pareto_cache: dict[int, tuple] = {}
        # per-claim-index HostPortUsage; populated only by the topo driver
        # when host ports are in play (plain solves gate ports shapes out)
        self._claim_hp: dict[int, HostPortUsage] = {}
        # min_active is set for real in _prepare_templates; abort() may run
        # before that (e.g. an ineligible shape found during grouping)
        self.min_active = False
        from karpenter_tpu.scheduler.scheduler import MIN_VALUES_POLICY_STRICT

        self.best_effort = scheduler.min_values_policy != MIN_VALUES_POLICY_STRICT
        self._saved_rm: Optional[tuple] = None
        # reserved-capacity flags are needed during grouping already (strict
        # mode makes every shape volatile on the topo driver)
        self.res_active = bool(
            scheduler.reserved_capacity_enabled
            and getattr(e, "_kt_has_reserved", False)
        )
        self.strict_res = _strict_reserved(scheduler)
        # strict-mode paths evaluate reservations PRE-commit (the evaluation
        # can raise at the host's can_add position) and stash the result
        # here for the commit hook; fallback mode leaves it None (computed
        # post-commit, identical by construction)
        self._pending_reserved: Optional[list] = None

    def abort(self) -> None:
        """Undo external state mutations before a host fallback. The plain
        solver mutates nothing outside itself until emit EXCEPT reservation
        bookkeeping; the topo driver overrides this to additionally restore
        topology counts/ownership."""
        self._restore_rm()

    def _restore_rm(self) -> None:
        if self._saved_rm is not None:
            rm = self.s.reservation_manager
            reservations, capacity = self._saved_rm
            rm._reservations = {h: set(ids) for h, ids in reservations.items()}
            rm._capacity = dict(capacity)

    # -- reserved offerings (fallback mode; nodeclaim.go:166-205,324-346) ----

    def _reserved_eval(
        self,
        hostname: str,
        reqs: Requirements,
        final_types: np.ndarray,
        fam: Optional[int] = None,
        current_reserved: Sequence = (),
    ) -> list:
        """The host's _offerings_to_reserve (nodeclaim.go:166-205) over a
        surviving-type mask: reserved offerings compatible with `reqs` that
        can still be reserved for `hostname`, in catalog order. In STRICT
        mode this raises the host's ReservedOfferingErrors — compatible but
        unreservable, or updated constraints stripping every held option."""
        rm = self.s.reservation_manager
        has_compatible = False
        out = []
        for i, offs in self.res_offs:
            if not final_types[i]:
                continue
            for oi, o in enumerate(offs):
                if not o.available:
                    continue
                if fam is not None:
                    key = (fam, i, oi)
                    ok = self._res_compat.get(key)
                    if ok is None:
                        ok = reqs.is_compatible(
                            o.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
                        )
                        self._res_compat[key] = ok
                else:
                    ok = reqs.is_compatible(
                        o.requirements, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
                    )
                if not ok:
                    continue
                has_compatible = True
                if rm.can_reserve(hostname, o):
                    out.append(o)
        if self.strict_res:
            from karpenter_tpu.scheduler.nodeclaim import (
                raise_strict_reserved_errors,
            )

            raise_strict_reserved_errors(has_compatible, out, current_reserved)
        return out

    def _final_types(self, type_mask: np.ndarray, u_ids: np.ndarray) -> np.ndarray:
        surv_u = np.zeros(self.U, dtype=bool)
        surv_u[u_ids] = True
        return type_mask & surv_u[self.uid_of_type]

    def _apply_reserved(self, c: "_Claim", updated: Optional[list] = None) -> None:
        """NodeClaim.add's reservation tail: reserve the fresh set, release
        ids that dropped out (nodeclaim.go:337-346). Strict callers pass the
        pre-commit-evaluated list (the evaluation may raise and must run at
        the host's can_add position); fallback mode computes it here, on the
        post-commit state — identical by construction."""
        if updated is None:
            updated = self._reserved_eval(
                c.hostname,
                self.fam_reqs[c.fam],
                self._final_types(c.type_mask, c.u_ids),
                fam=c.fam,
            )
        rm = self.s.reservation_manager
        rm.reserve(c.hostname, *updated)
        updated_ids = {o.reservation_id for o in updated}
        for o in c.reserved:
            if o.reservation_id not in updated_ids:
                rm.release(c.hostname, o)
        c.reserved = updated

    def _materialize(self, c: "_Claim") -> None:
        """Collapse a claim's deferred joins into the standard rem/u_ids
        narrowing. Exact: a row survives the iterative per-join pruning iff
        it fits the accumulated usage (the prune criterion telescopes dim by
        dim — usage only grows), so one vectorized pass reproduces the whole
        sequence."""
        extra = c.defer[1]
        c.defer = None
        if any(extra):
            cur = c.rem - np.asarray(extra)
            keep = (cur >= -_EPS).all(axis=1)
            if keep.all():
                c.rem = cur
            else:
                c.rem = cur[keep]
                c.u_ids = c.u_ids[keep]

    def _pareto_for(self, rem: np.ndarray) -> list:
        """Pareto-maximal rows of an open-time headroom matrix as Python
        lists — any-row-fits is equivalent to any-PARETO-row-fits, and the
        maximal set is tiny. Cached by matrix identity: memoized openings
        share one matrix across thousands of claims."""
        cache = self._pareto_cache
        hit = cache.get(id(rem))
        if hit is not None:
            return hit[0]
        rows = rem.tolist()
        pareto: list = []
        for r in sorted(rows, key=sum, reverse=True):
            if not any(
                all(p[d] >= r[d] for d in range(len(r))) for p in pareto
            ):
                pareto.append(r)
        cache[id(rem)] = (pareto, rem)  # hold rem so its id can't recycle
        return pareto

    def _order_hook_add(self, ci: int) -> None:
        """Claim-order observer: a claim was opened (index ci). The topo
        driver maintains an incremental host-scan order; a no-op here."""

    def _order_hook_move(self, ci: int, old_key: tuple, new_key: tuple) -> None:
        """Claim-order observer: claim ci's (count, rank, ci) key changed."""

    def _intern_fam(self, rows: frozenset, reqs: Requirements) -> int:
        """Intern a requirement row-set; `reqs` must be the hostname-free
        requirement set whose interned rows are exactly `rows`."""
        fam = self.fam_ids.get(rows)
        if fam is None:
            fam = len(self.fam_rows)
            self.fam_ids[rows] = fam
            self.fam_rows.append(rows)
            self.fam_reqs.append(reqs)
        return fam

    # -- encoding ------------------------------------------------------------

    def _group_pods(self) -> Optional[np.ndarray]:
        """Collapse pods into value-identical shape groups; PodData is
        computed ONCE per group (the per-pod host parse is the single
        biggest cost at 50k pods). Returns the per-pod group-index array, or
        None when a shape fails the per-group eligibility gates (→ host
        path). Group numbering follows interned-signature order — decisions
        never depend on it (only pod queue order matters)."""
        s, dims = self.s, self.dims
        pods = self.pods
        # the spec signature is immutable alongside the spec; pods resolve
        # across provisioner passes, so its interned id is cached on the
        # object (invalidated at spec mutation sites as _kt_sig)
        try:
            sigs = [p._kt_sig for p in pods]
        except AttributeError:
            sigs = []
            for pod in pods:
                sig = getattr(pod, "_kt_sig", None)
                if sig is None:
                    raw = _raw_sig(pod)
                    sig = _SIG_IDS.get(raw)
                    if sig is None:
                        if len(_SIG_IDS) >= _SIG_CAP:
                            _SIG_IDS.clear()
                        sig = next(_SIG_NEXT)
                        _SIG_IDS[raw] = sig
                    try:
                        pod._kt_sig = sig
                    except Exception:  # noqa: BLE001 — slotted/frozen pod
                        pass
                sigs.append(sig)
        _, first_idx, inverse, counts = np.unique(
            np.asarray(sigs, dtype=np.int64),
            return_index=True,
            return_inverse=True,
            return_counts=True,
        )
        for k, fi in enumerate(first_idx):
            pod = pods[int(fi)]
            if not _group_eligible(pod):
                return None
            s.update_cached_pod_data(pod)
            data = s.cached_pod_data[pod.metadata.uid]
            if any(name not in dims for name in data.requests):
                return None
            group = _Group(data, dims)
            if group.has_hostname:
                # per-claim hostname placeholders defeat family sharing;
                # hostname-pinned pods are rare — host path
                return None
            group.n_pods = int(counts[k])
            self.groups.append(group)
        G = len(self.groups)
        self.gheaps = [[] for _ in range(G)]
        self.gsynced = [0] * G
        self.nptr = [0] * G
        return inverse.astype(np.int32)

    # single-slot: steady-state passes re-solve the latest batch; holding
    # more would pin old pod sets in memory for the process lifetime
    _ORDER_CACHE: dict = {}

    def _order(self, gi_arr: np.ndarray) -> np.ndarray:
        """Exact host queue order (queue.go:72-108): cpu desc, mem desc,
        creation timestamp, uid. Vectorized via lexsort (numpy string
        comparison is code-point order — identical to Python's). Returns
        the permutation of pod indices.

        The permutation is memoized per (pod identities, shape signatures,
        group sort keys): steady-state provisioner passes re-solve the same
        pod set, whose uids/timestamps are immutable and whose effective
        shapes are pinned by the signature bytes in the key."""
        groups = self.groups
        pods = self.pods
        key = None
        try:
            key = (
                tuple(map(id, pods)),
                gi_arr.tobytes(),
                tuple((g.sort_cpu, g.sort_mem) for g in groups),
            )
            hit = self._ORDER_CACHE.get(key)
            if hit is not None:
                return hit[0]
        except (TypeError, ValueError):
            pass
        order = self._order_compute(gi_arr)
        if key is not None:
            self._ORDER_CACHE.clear()
            # hold the pods so their ids can't recycle while cached
            self._ORDER_CACHE[key] = (order, list(pods))
        return order

    def _order_compute(self, gi_arr: np.ndarray) -> np.ndarray:
        groups = self.groups
        pods = self.pods
        try:
            cpu = np.array([g.sort_cpu for g in groups])[gi_arr]
            mem = np.array([g.sort_mem for g in groups])[gi_arr]
            ts = np.fromiter(
                (p.metadata.creation_timestamp for p in pods),
                dtype=np.float64,
                count=len(pods),
            )
            uid = np.array([p.metadata.uid for p in pods])
            return np.lexsort((uid, ts, -mem, -cpu))
        except (TypeError, ValueError):
            return np.array(
                sorted(
                    range(len(pods)),
                    key=lambda i: (
                        -groups[gi_arr[i]].sort_cpu,
                        -groups[gi_arr[i]].sort_mem,
                        pods[i].metadata.creation_timestamp,
                        pods[i].metadata.uid,
                    ),
                ),
                dtype=np.int64,
            )

    def _rows_sans_hostname(self, reqs: Requirements) -> frozenset:
        rid = self.engine.row_id
        return frozenset(
            rid(r) for r in reqs if r.key != wk.LABEL_HOSTNAME
        )

    @staticmethod
    def _sans_hostname(reqs: Requirements) -> Requirements:
        """Canonical hostname-free copy — the form every engine-level cache
        (solver_fam_trans, family interning) keys on; all canonicalization
        sites must share this ONE definition."""
        return Requirements(*(r for r in reqs if r.key != wk.LABEL_HOSTNAME))

    def _prepare_templates(self) -> None:
        """Template masks/overheads + the batched device sweep over all
        compatible (template x group) joint requirement sets — the
        MXU-shaped part of the solve (SURVEY.md §7 step 2)."""
        s, e = self.s, self.engine
        T = len(s.nodeclaim_templates)
        G = len(self.groups)
        self.tmpl_mask = np.zeros((T, self.I), dtype=bool)
        self.tmpl_options: list[list] = []
        self.usage0_f = np.zeros((T, self.D), dtype=np.float64)
        # minValues specs per template: only template rows carry minValues
        # (pods can't set it; joint merges keep the template's via max-merge),
        # so the per-claim check is fully determined by (ti, surviving types)
        self.tmpl_min: list[list[tuple[str, int]]] = [
            [
                (r.key, r.min_values)
                for r in s.nodeclaim_templates[ti].requirements
                if r.min_values is not None
            ]
            for ti in range(T)
        ]
        self.min_active = any(self.tmpl_min)
        # reserved-capacity bookkeeping: per-type reserved offerings in
        # catalog order + a snapshot of the ReservationManager so a
        # fallback abort leaves the host loop uncorrupted state
        if self.res_active:
            self.res_offs: list[tuple[int, list]] = []
            for i, it in enumerate(e.instance_types):
                if it.has_reserved_offerings:
                    self.res_offs.append(
                        (
                            i,
                            [
                                o
                                for o in it.offerings
                                if o.capacity_type == wk.CAPACITY_TYPE_RESERVED
                            ],
                        )
                    )
            self._res_compat: dict[tuple[int, int, int], bool] = {}
            rm = s.reservation_manager
            self._saved_rm = (
                {h: set(ids) for h, ids in rm._reservations.items()},
                dict(rm._capacity),
            )
        index = {id(it): i for i, it in enumerate(e.instance_types)}
        name_index = {it.name: i for i, it in enumerate(e.instance_types)}
        self.opt_index: list[list[int]] = []
        for g in self.groups:
            g.rowset = self._rows_sans_hostname(g.reqs)
        for ti, nct in enumerate(s.nodeclaim_templates):
            idxs = []
            for it in nct.instance_type_options:
                i = index.get(id(it))
                if i is None:
                    i = name_index.get(it.name)
                if i is None:
                    raise _Fallback("template option missing from engine catalog")
                idxs.append(i)
                self.tmpl_mask[ti, i] = True
            self.opt_index.append(idxs)
            self.tmpl_options.append(list(nct.instance_type_options))
            for name, v in s.daemon_overhead[nct].items():
                self.usage0_f[ti, self.dims[name]] = v
        # Joint (template x group) requirement sets, evaluated in ONE batched
        # device sweep — the [T*G, I] membership-matmul cube. Shared with
        # solverd's coalescer: prime_joint_masks is the single sweep
        # implementation, _joint_pairs the single domain enumeration.
        pairs = self._joint_pairs()
        if pairs is not None:
            prime_joint_masks(e, pairs)

    def _joint_pairs(self) -> Optional[list[tuple]]:
        """All compatible (template x group) joint (rows, Requirements)
        pairs — this solve's sweep domain. None for degenerate solves with a
        huge distinct-shape count, which fall back to lazy per-pair host
        evaluation (still exact) to bound the batch."""
        T = len(self.s.nodeclaim_templates)
        G = len(self.groups)
        if T * G > 8192:
            return None
        out: list[tuple] = []
        for ti in range(T):
            for gi in range(G):
                tg = self._tg(ti, gi)
                if tg is not None:
                    joint, rows = tg
                    out.append((rows, joint))
        return out

    _MISSING = object()

    def _tg(self, ti: int, gi: int):
        """(joint Requirements, engine row-set) for template x group, or None
        when the template's requirements reject the group."""
        key = (ti, gi)
        got = self.tg_compat.get(key, self._MISSING)
        if got is self._MISSING:
            nct = self.s.nodeclaim_templates[ti]
            g = self.groups[gi]
            err = nct.requirements.compatible(
                g.reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
            )
            if err is not None:
                got = None
            else:
                joint = Requirements(*nct.requirements.values())
                joint.add(*g.reqs.values())
                got = (joint, self._rows_sans_hostname(joint))
            self.tg_compat[key] = got
        return got

    # -- joint masks ---------------------------------------------------------

    def _joint_masks(self, rows: frozenset, reqs: Requirements) -> tuple:
        global JOINT_CACHE_HITS, JOINT_CACHE_MISSES
        cache = self.joint_cache
        got = cache.get(rows)
        if got is None:
            JOINT_CACHE_MISSES += 1
            keys = [r.key for r in reqs if r.key != wk.LABEL_HOSTNAME]
            got = self.engine.masks_for_rows(list(rows), keys)
        else:
            JOINT_CACHE_HITS += 1
            # LRU touch: reinsertion moves the entry to the recency tail so
            # _evict_lru sheds cold entries first
            del cache[rows]
        cache[rows] = got
        return got

    # -- existing nodes (addToExistingNode, scheduler.go:451-473) ------------

    def _try_nodes(self, pod: Pod, g: _Group, gi: int) -> bool:
        nodes = self.nodes
        j = self.nptr[gi]
        N = len(nodes)
        while j < N:
            nd = nodes[j]
            tol = nd.gtol.get(gi)
            if tol is None:
                tol = Taints(nd.en.cached_taints).tolerates_pod(pod) is None
                nd.gtol[gi] = tol
            if not tol:
                j += 1
                continue
            cc = nd.gcompat.get(gi)
            if cc is None or cc[0] != nd.version:
                ok = nd.reqs.compatible(g.reqs) is None
                nd.gcompat[gi] = (nd.version, ok)
            else:
                ok = cc[1]
            if not ok:
                # requirements only narrow: permanently incompatible
                j += 1
                continue
            kc = nd.gcap.get(gi)
            if kc is None or kc[0] != nd.usage_ver:
                k = self._node_capacity(nd, g)
            else:
                k = kc[1]
            if k <= 0:
                # remaining resources only shrink: permanently full
                j += 1
                continue
            # join
            self.nptr[gi] = j
            self._joined_node = nd
            nd.joined.append(pod)
            nd.remaining = res.subtract(nd.remaining, g.requests)
            narrowed = any(
                not nd.reqs.has(r.key) or nd.reqs.get(r.key) != r for r in g.reqs
            )
            if narrowed:
                joint = Requirements(*nd.reqs.values())
                joint.add(*g.reqs.values())
                nd.reqs = joint
                nd.version += 1
            nd.usage_ver += 1
            nd.gcap[gi] = (nd.usage_ver, k - 1)
            return True
        self.nptr[gi] = j
        return False

    def _node_capacity(self, nd: _Node, g: _Group) -> int:
        k = _BIG
        remaining = nd.remaining
        for name, v in g.requests.items():
            if v <= 0:
                continue
            have = remaining.get(name, 0.0)
            k = min(k, int((have + _EPS) // v))
            if k <= 0:
                return 0
        return int(k)

    # -- in-flight claims (addToInflightNode, scheduler.go:510-543) ----------

    def _try_claims(self, pod: Pod, g: _Group, gi: int) -> bool:
        claims = self.claims
        heap = self.gheaps[gi]
        synced = self.gsynced[gi]
        if synced < len(claims):
            for ci in range(synced, len(claims)):
                c = claims[ci]
                heapq.heappush(heap, (c.count, c.rank, ci))
            self.gsynced[gi] = len(claims)
        req_f = g.req_f
        fit_floor = g.fit_floor  # req_f - eps, precomputed
        while heap:
            count, rank, ci = heap[0]
            c = claims[ci]
            if c.defer is not None:
                self._materialize(c)
            if gi in c.gdrop:
                heapq.heappop(heap)
                continue
            if c.count != count or c.rank != rank:
                heapq.heapreplace(heap, (c.count, c.rank, ci))
                continue
            if gi in c.gknown:
                # steady state: requirements already subsumed; one small
                # compare against the remaining-headroom matrix decides
                fitrows = (c.rem >= fit_floor).all(axis=1)
                if not fitrows.any():
                    c.gdrop.add(gi)  # usage only grows: permanently full
                    heapq.heappop(heap)
                    continue
                # a fit-shrunk option set can newly violate minValues (the
                # host re-filters on every can_add); unchanged sets passed
                # when the claim last changed
                if (
                    self.min_active
                    and not fitrows.all()
                    and not self._min_join_ok(c, c.u_ids[fitrows])
                ):
                    c.gdrop.add(gi)  # diversity only shrinks: permanent
                    heapq.heappop(heap)
                    continue
            else:
                fitrows = self._try_first_join(c, pod, g, gi)
                if fitrows is None:
                    c.gdrop.add(gi)  # all rejection reasons are monotone
                    heapq.heappop(heap)
                    continue
            # join: usage grows by req_f; rows that no longer fit the NEW
            # usage (exactly the rows failing this fit check) die forever
            if fitrows.all():
                c.rem -= req_f
            else:
                c.rem = c.rem[fitrows] - req_f
                c.u_ids = c.u_ids[fitrows]
            c.count = count + 1
            self.seq += 1
            c.rank = -self.seq
            c.members.append(pod)
            c.group_counts[gi] = c.group_counts.get(gi, 0) + 1
            heapq.heapreplace(heap, (c.count, c.rank, ci))
            self._joined = c
            self._order_hook_move(ci, (count, rank, ci), (c.count, c.rank, ci))
            if self.res_active:
                self._apply_reserved(c, self._pending_reserved)
                self._pending_reserved = None
            return True
        return False

    _REJECT, _SAME, _NARROW = 0, 1, 2

    def _try_first_join(self, c: _Claim, pod: Pod, g: _Group, gi: int):
        """First join of group g onto claim c: the full NodeClaim.can_add
        gate sequence (nodeclaim.go:114-163). Returns the fit-row mask over
        the claim's (possibly narrowed) headroom matrix, or None to reject
        permanently. Commits requirement narrowing on success.

        The requirement algebra — compatibility, joint construction, joint
        masks — depends only on (claim requirement family, group), so its
        outcome is memoized as a family TRANSITION; per-claim work is a few
        small-array ops. Hostname placeholders never participate: groups
        constraining hostname are gated to the host path."""
        tol = self.tg_tol.get((c.ti, gi))
        if tol is None:
            nct = self.s.nodeclaim_templates[c.ti]
            tol = Taints(nct.spec.taints).tolerates_pod(pod) is None
            self.tg_tol[(c.ti, gi)] = tol
        if not tol:
            return None
        ent = self.fam_join.get((c.fam, gi))
        if ent is None:
            ent = self._build_fam_join(c.fam, gi)
        kind = ent[0]
        if kind == self._REJECT:
            return None
        if kind == self._NARROW:
            new_mask = c.type_mask & ent[2]
            # unique-alloc rows that still have a surviving type
            surv_u = np.zeros(self.U, dtype=bool)
            surv_u[self.uid_of_type[new_mask]] = True
            keep = surv_u[c.u_ids]
            fitrows = keep & (c.rem >= g.fit_floor).all(axis=1)
            if not fitrows.any():
                return None
            if self.min_active and not self._min_join_ok(
                c, c.u_ids[fitrows], new_mask
            ):
                return None
            # commit the requirement-level narrowing (host narrows options on
            # every successful Add with the joint set)
            c.type_mask = new_mask
            c.rem = c.rem[keep]
            c.u_ids = c.u_ids[keep]
            c.fam = ent[1]
            c.gknown.add(gi)
            return fitrows[keep]
        fitrows = (c.rem >= g.fit_floor).all(axis=1)
        if not fitrows.any():
            return None
        if (
            self.min_active
            and not fitrows.all()
            and not self._min_join_ok(c, c.u_ids[fitrows])
        ):
            return None
        c.gknown.add(gi)
        return fitrows

    def _build_fam_join(self, fam: int, gi: int) -> tuple:
        """Memoized family transition for group gi joining a claim of family
        fam: reject (incompatible), same (joint row-set unchanged — adding
        the group narrows nothing), or narrow (new family id + the combined
        compat∧offering mask to AND into the claim's options).

        The requirement algebra is a pure function of the two row-sets, so
        its outcome is cached on the ENGINE across solves (steady-state
        passes re-derive identical transitions); only the per-solve family
        id interning and the mask AND run per solve."""
        g = self.groups[gi]
        base_rows = self.fam_rows[fam]
        ckey = (base_rows, g.rowset)
        cached = self.engine.solver_fam_trans.get(ckey)
        if cached is None:
            base = self.fam_reqs[fam]
            if base.compatible(g.reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS) is not None:
                cached = (self._REJECT, None, None)
            elif g.rowset <= base_rows:
                # every group row IS the claim's row for that key
                cached = (self._SAME, None, None)
            else:
                joint = Requirements(*base.values())
                joint.add(*g.reqs.values())
                rows = self._rows_sans_hostname(joint)
                if rows == base_rows:
                    cached = (self._SAME, None, None)
                else:
                    # canonical = hostname-free: the cache key strips
                    # hostname, so two groups differing only in a hostname
                    # pin share this entry — the claim's own placeholder row
                    # is re-added by the consumers that need it. Shared
                    # read-only across solves — callers copy.
                    cached = (self._NARROW, rows, self._sans_hostname(joint))
            _evict_lru(self.engine.solver_fam_trans, _ENGINE_CACHE_CAP)
            self.engine.solver_fam_trans[ckey] = cached
        else:
            # LRU touch (see _evict_lru): keep steady-state transitions warm
            del self.engine.solver_fam_trans[ckey]
            self.engine.solver_fam_trans[ckey] = cached
        kind, rows, joint = cached
        if kind == self._NARROW:
            compat_v, offer_v = self._joint_masks(rows, joint)
            new_fam = self._intern_fam(rows, joint)
            # trailing joint: the merged pre-topology requirement set,
            # reused by the topo driver (never mutated — callers copy)
            ent = (self._NARROW, new_fam, compat_v & offer_v, joint)
        else:
            ent = (kind,)
        self.fam_join[(fam, gi)] = ent
        return ent

    # -- new claims (addToNewNodeClaim, scheduler.go:478-556) ----------------

    def _ensure_open_entry(self, ti: int, gi: int) -> tuple:
        """Memoized LIMITLESS opening per (ti, gi): candidate set, fitting
        unique-alloc rows, headroom matrix, and the no-limits minValues
        outcome. Limits are applied per open as a cheap type-mask AND —
        narrowing types never changes a surviving row's headroom, so the
        limited open is a row-subset of the limitless one. Entries with
        fam < 0 are permanent failures (error stashed in _open_errs).
        Callers must have checked `_tg(ti, gi) is not None`. Shared by the
        host walk's _new_claim and the fused builder's opening tables."""
        okey = (ti, gi)
        entry = self.open_cache.get(okey)
        if entry is not None:
            return entry
        g = self.groups[gi]
        joint_tg, rows = self._tg(ti, gi)
        compat_v, offer_v = self._joint_masks(rows, joint_tg)
        base = self.tmpl_mask[ti]
        candidate0 = base & compat_v & offer_v
        cand_u = np.unique(self.uid_of_type[candidate0])
        rem0 = self.uniq_alloc[cand_u] - (self.usage0_f[ti] + g.req_f)
        fitrows = (rem0 >= -_EPS).all(axis=1)
        if not fitrows.any():
            # no limits will ever fix an empty limitless set
            err = self._filter_error(base, compat_v, offer_v, ti, g)
            self.open_cache[okey] = entry = (-1, None, None, None, None, False)
            self._open_errs[okey] = err
            return entry
        min_specs0, min_relaxed0, msg = self.tmpl_min[ti], False, None
        if self.min_active and self.tmpl_min[ti]:
            surv_u = np.zeros(self.U, dtype=bool)
            surv_u[cand_u[fitrows]] = True
            min_specs0, min_relaxed0, msg = self._min_open(
                ti, candidate0 & surv_u[self.uid_of_type]
            )
        if msg is not None:
            # strict-policy failure on the FULL set is permanent
            err = self._filter_error(base, compat_v, offer_v, ti, g)
            err.min_values_incompatible = msg
            self.open_cache[okey] = entry = (-1, None, None, None, None, False)
            self._open_errs[okey] = err
            return entry
        fam = self._intern_fam(rows, joint_tg)
        self.open_cache[okey] = entry = (
            fam, candidate0, cand_u[fitrows], rem0[fitrows],
            min_specs0, min_relaxed0,
        )
        return entry

    def _new_claim(self, pod: Pod, g: _Group, gi: int) -> Optional[Exception]:
        cached = self.gnewclaim_err.get(gi)
        if cached is not None and cached[0] == self.limits_version:
            if cached[2] is not None:
                # every pod of the group shares the cached diagnosis, but
                # each stages its OWN funnel (commit is keyed by pod uid)
                explmod.recorder().note_funnel(pod.metadata.uid, cached[2])
            return cached[1]
        s = self.s
        # errs carries (nodepool, error): the pool attribution feeds the
        # explanation funnel; the joined message is unchanged
        errs: list[tuple[str, Exception]] = []
        for ti, nct in enumerate(s.nodeclaim_templates):
            remaining = self.remaining_resources.get(nct.nodepool_name)
            limits_mask = None
            if remaining:
                limits_mask = self._limits_mask(nct.nodepool_name, remaining)
                # exhaustion check cached per (template, pool version): an
                # exhausted pool costs one dict hit per scan, not an array
                # reduction + fresh exception
                akey = (ti, self.pool_limits_ver.get(nct.nodepool_name, 0))
                hit = self._limits_any.get(akey)
                if hit is None:
                    hit = self._limits_any[akey] = (
                        bool((limits_mask & self.tmpl_mask[ti]).any())
                        or ValueError(
                            f"all available instance types exceed limits for "
                            f"nodepool {nct.nodepool_name!r}"
                        )
                    )
                if hit is not True:
                    errs.append((nct.nodepool_name, hit))
                    continue
            tol = self.tg_tol.get((ti, gi))
            if tol is None:
                terr = Taints(nct.spec.taints).tolerates_pod(pod)
                tol = terr is None
                self.tg_tol[(ti, gi)] = tol
            if not tol:
                errs.append(
                    (
                        nct.nodepool_name,
                        ValueError(str(Taints(nct.spec.taints).tolerates_pod(pod))),
                    )
                )
                continue
            tg = self._tg(ti, gi)
            if tg is None:
                errs.append(
                    (
                        nct.nodepool_name,
                        ValueError(
                            "incompatible requirements, "
                            + str(
                                nct.requirements.compatible(
                                    g.reqs, ALLOW_UNDEFINED_WELL_KNOWN_LABELS
                                )
                            )
                        ),
                    )
                )
                continue
            entry = self._ensure_open_entry(ti, gi)
            fam, candidate0, u_ids0, rem0_fit0, min_specs, min_relaxed = entry
            okey = (ti, gi)
            if fam < 0:
                if limits_mask is None:
                    errs.append((nct.nodepool_name, self._open_errs[okey]))
                else:
                    # host diagnostics are over the LIMITED base; a limited
                    # set is a subset of the failed limitless one, so it
                    # still fails — recompute only the message bits
                    errs.append(
                        (
                            nct.nodepool_name,
                            self._limited_open_error(ti, gi, g, limits_mask),
                        )
                    )
                continue
            if limits_mask is None:
                self._open_claim(
                    ti, fam, pod, gi, candidate0, u_ids0, rem0_fit0.copy(),
                    reusable=True, min_specs=min_specs, min_relaxed=min_relaxed,
                )
                return None
            # derived limited opening, cached per (entry, mask identity):
            # the mask object is stable while the pool's budget stays
            # within one capacity threshold (see _limits_mask), so most
            # opens of a limited pool reuse one derived set — and the
            # arrays stay alive here, keeping native packings id-safe
            dkey = (ti, gi, id(limits_mask))
            derived = self._limited_open_cache.get(dkey)
            if derived is None:
                candidate = candidate0 & limits_mask
                live = np.zeros(self.U, dtype=bool)
                live[self.uid_of_type[candidate]] = True
                sel = live[u_ids0]
                u_ids = u_ids0[sel]
                # the minValues gate is fully determined by the derived set —
                # evaluate once per dkey, not per open
                mspecs, mrelax, mmsg = min_specs, min_relaxed, None
                if u_ids.size and self.min_active and self.tmpl_min[ti]:
                    surv_u = np.zeros(self.U, dtype=bool)
                    surv_u[u_ids] = True
                    mspecs, mrelax, mmsg = self._min_open(
                        ti, candidate & surv_u[self.uid_of_type]
                    )
                derived = (candidate, sel, u_ids, mspecs, mrelax, mmsg, limits_mask)
                self._limited_open_cache[dkey] = derived
            candidate, sel, u_ids, min_specs, min_relaxed, min_msg, _alive = derived
            if u_ids.size == 0:
                # limited set empty: recompute the host's exact diagnostics
                joint_tg, rows = tg
                compat_v, offer_v = self._joint_masks(rows, joint_tg)
                errs.append(
                    (
                        nct.nodepool_name,
                        self._filter_error(
                            self.tmpl_mask[ti] & limits_mask, compat_v, offer_v,
                            ti, g,
                        ),
                    )
                )
                continue
            if min_msg is not None:
                joint_tg, rows = tg
                compat_v, offer_v = self._joint_masks(rows, joint_tg)
                err = self._filter_error(
                    self.tmpl_mask[ti] & limits_mask, compat_v, offer_v, ti, g
                )
                err.min_values_incompatible = min_msg
                errs.append((nct.nodepool_name, err))
                continue
            self._open_claim(
                ti,
                fam,
                pod,
                gi,
                candidate,
                u_ids,
                rem0_fit0[sel].copy(),
                reusable=True,
                min_specs=min_specs,
                min_relaxed=min_relaxed,
            )
            surv_u = np.zeros(self.U, dtype=bool)
            surv_u[u_ids] = True
            self._subtract_max(nct, candidate & surv_u[self.uid_of_type])
            return None
        if not errs:
            errs.append(("", ValueError("no nodepool can host the pod")))
        err = (
            errs[0][1]
            if len(errs) == 1
            else ValueError("; ".join(str(e) for _, e in errs))
        )
        rec = explmod.recorder()
        funnel = explmod.funnel_from(errs) if rec.enabled else None
        if funnel is not None:
            rec.note_funnel(pod.metadata.uid, funnel)
        self.gnewclaim_err[gi] = (self.limits_version, err, funnel)
        return err

    def _open_claim(
        self,
        ti: int,
        fam: int,
        pod: Pod,
        gi: int,
        candidate: np.ndarray,
        u_ids: np.ndarray,
        rem: np.ndarray,
        reusable: bool = False,
        hostname: Optional[str] = None,
        min_specs: Optional[list] = None,
        min_relaxed: bool = False,
        pareto: Optional[list] = None,
    ) -> None:
        """Register a freshly opened claim with the active driver (Python
        loop or native kernel); the opening pod is its first member.
        `reusable` marks candidate/u_ids arrays shared via open_cache (the
        native driver caches their packed encodings only then). The topo
        driver supplies `hostname` (drawn from the host scheduler's counter
        for sorted-domain-iteration parity); plain solves use the device
        counter — placeholder strings are decision-inert without topology."""
        if hostname is None:
            hostname = f"device-placeholder-{next(_placeholder_counter):04d}"
        if self._native is not None:
            self._native.add_claim(
                ti, fam, hostname, pod, gi, candidate, u_ids, rem, reusable
            )
            return
        self.seq += 1
        c = _Claim(ti, fam, hostname, candidate, u_ids, rem, self.seq)
        c.min_specs = self.tmpl_min[ti] if min_specs is None else min_specs
        c.min_relaxed = min_relaxed
        if self._defer_ok:
            c.defer = (
                pareto if pareto is not None else self._pareto_for(rem),
                [0.0] * self.D,
            )
        c.count = 1
        c.members.append(pod)
        c.group_counts[gi] = 1
        c.gknown.add(gi)
        self.claims.append(c)
        self._order_hook_add(len(self.claims) - 1)
        if self.res_active:
            self._apply_reserved(c, self._pending_reserved)
            self._pending_reserved = None

    def _limited_open_error(
        self, ti: int, gi: int, g: _Group, limits_mask: np.ndarray
    ) -> Exception:
        """Host-identical opening failure over the LIMITS-NARROWED base —
        the slow path for the rare template whose limitless opening already
        failed (the limited subset fails too; only the diagnostic bits can
        differ)."""
        joint_tg, rows = self._tg(ti, gi)
        compat_v, offer_v = self._joint_masks(rows, joint_tg)
        base = self.tmpl_mask[ti] & limits_mask
        candidate = base & compat_v & offer_v
        cand_u = np.unique(self.uid_of_type[candidate])
        rem0 = self.uniq_alloc[cand_u] - (self.usage0_f[ti] + g.req_f)
        fitrows = (rem0 >= -_EPS).all(axis=1)
        err = self._filter_error(base, compat_v, offer_v, ti, g)
        if fitrows.any() and self.min_active and self.tmpl_min[ti]:
            surv_u = np.zeros(self.U, dtype=bool)
            surv_u[cand_u[fitrows]] = True
            _, _, msg = self._min_open(ti, candidate & surv_u[self.uid_of_type])
            if msg is not None:
                err.min_values_incompatible = msg
        return err

    def _limits_mask(self, pool_name: str, remaining: dict) -> np.ndarray:
        """Types whose CAPACITY fits inside the nodepool's remaining limits
        (scheduler.go:670-686; _filter_by_remaining_resources). Cached per
        pool until _subtract_max moves that pool's budget."""
        ver = self.pool_limits_ver.get(pool_name, 0)
        hit = self._limits_mask_cache.get(pool_name)
        if hit is not None and hit[0] == ver:
            return hit[1]
        mask = np.ones(self.I, dtype=bool)
        for name, limit in remaining.items():
            d = self.dims.get(name)
            if d is None:
                if 0.0 > limit + _EPS:
                    mask[:] = False
            else:
                mask &= self.cap_f[:, d] <= limit + _EPS
        if hit is not None and np.array_equal(hit[1], mask):
            # content unchanged (budget moved without crossing a capacity
            # threshold): keep the OLD array object so identity-keyed
            # downstream caches (derived opens, native packings) stay hot
            mask = hit[1]
        self._limits_mask_cache[pool_name] = (ver, mask)
        return mask

    def _subtract_max(self, nct, types_mask: np.ndarray) -> None:
        """Pessimistic nodepool-limit tracking: subtract the max CAPACITY
        over the claim's narrowed options (scheduler.go:744-765)."""
        remaining = self.remaining_resources.get(nct.nodepool_name)
        if not remaining:
            return
        if types_mask.any():
            maxes = self.cap_f[types_mask].max(axis=0)
        else:
            maxes = np.zeros(self.D)
        self.remaining_resources[nct.nodepool_name] = {
            k: (v - maxes[self.dims[k]] if k in self.dims else v)
            for k, v in remaining.items()
        }
        self.limits_version += 1
        self.pool_limits_ver[nct.nodepool_name] = (
            self.pool_limits_ver.get(nct.nodepool_name, 0) + 1
        )

    # -- minValues (nodeclaim.go:425-436, types.go:190-224) ------------------

    def _min_counts(
        self, specs: list[tuple[str, int]], surv_types: np.ndarray
    ) -> list[tuple[str, int, int]]:
        """(key, needed, distinct type-declared value count) per spec over a
        surviving-type mask (types.go:190-224 counting)."""
        out = []
        for key, needed in specs:
            M = self.engine.value_matrix(key)
            count = int(M[:, surv_types].any(axis=1).sum()) if M.size else 0
            out.append((key, needed, count))
        return out

    def _min_fail(
        self, specs: list[tuple[str, int]], surv_types: np.ndarray
    ) -> Optional[str]:
        """The host's strict minValues gate over a surviving-type mask:
        None when every minValues key counts enough distinct type-declared
        values, else the host's error message. The host skips the check
        entirely when `remaining` is empty (satisfies_min_values returns no
        error for zero types) — callers only reach here with a non-empty
        surviving set."""
        bad = [k for k, needed, count in self._min_counts(specs, surv_types)
               if count < needed]
        if bad:
            from karpenter_tpu.cloudprovider.types import min_values_error

            return min_values_error(bad)
        return None

    def _min_open(
        self, ti: int, surv_types: np.ndarray
    ) -> tuple[list[tuple[str, int]], bool, Optional[str]]:
        """MinValues at claim open: (claim specs, relaxed?, error). Strict
        policy rejects when the count falls short; BestEffort instead writes
        the spec down to the achievable count (nodeclaim.go:425-436) so the
        open always succeeds and later joins gate on the relaxed value."""
        counted = self._min_counts(self.tmpl_min[ti], surv_types)
        if not self.best_effort:
            bad = [k for k, needed, count in counted if count < needed]
            if bad:
                from karpenter_tpu.cloudprovider.types import min_values_error

                return self.tmpl_min[ti], False, min_values_error(bad)
            return self.tmpl_min[ti], False, None
        specs = [(k, min(needed, count)) for k, needed, count in counted]
        relaxed = any(count < needed for _, needed, count in counted)
        return specs, relaxed, None

    def _min_join_ok(self, c: "_Claim", new_u: np.ndarray, new_mask=None) -> bool:
        """Would claim c still satisfy its (possibly open-relaxed) minValues
        after a join that leaves unique-alloc rows `new_u` (and optionally
        narrows the type mask)? Monotone: the specs are fixed at open and
        narrowing only shrinks counts, so once False for a (claim, group)
        pair it stays False — callers may reject permanently."""
        if not c.min_specs:
            return True
        mask = c.type_mask if new_mask is None else new_mask
        surv_u = np.zeros(self.U, dtype=bool)
        surv_u[new_u] = True
        return self._min_fail(c.min_specs, mask & surv_u[self.uid_of_type]) is None

    def _filter_error(
        self,
        base: np.ndarray,
        compat_v: np.ndarray,
        offer_v: np.ndarray,
        ti: int,
        g: _Group,
    ) -> InstanceTypeFilterError:
        """Host-identical three-criteria diagnostics over the limits-filtered
        option set (nodeclaim.go:247-441)."""
        fits_v = self._fits_vec(self.usage0_f[ti] + g.req_f)
        m = base
        c, f, o = compat_v[m], fits_v[m], offer_v[m]
        rec = explmod.recorder()
        if rec.enabled:
            # decode the cube's already-materialized planes into per-stage
            # elimination counts (first-failing-stage attribution) — host
            # numpy over fetched bools, zero extra device dispatches
            from karpenter_tpu.ops import feasibility as feas

            rec.note_plane_counts(feas.stage_counts(feas.stage_plane_np(c, f, o)))
        err = InstanceTypeFilterError()
        err.requirements_met = bool(c.any())
        err.fits = bool(f.any())
        err.has_offering = bool(o.any())
        err.requirements_and_fits = bool((c & f & ~o).any())
        err.requirements_and_offering = bool((c & o & ~f).any())
        err.fits_and_offering = bool((f & o & ~c).any())
        return err

    def _fits_vec(self, requests_f: np.ndarray) -> np.ndarray:
        pos = np.nonzero(requests_f > 0)[0]
        if not pos.size:
            return np.ones(self.I, dtype=bool)
        return np.all(
            requests_f[pos][None, :] <= self.alloc_f[:, pos] + _EPS, axis=1
        )

    # -- main loop (Scheduler._solve, scheduler.go:346-429) ------------------

    def run(self, timeout: Optional[float]) -> None:
        gi_arr = self._group_pods()
        if gi_arr is None:
            raise _IneligibleShape("ineligible pod shape")
        self._prepare_templates()
        order = self._order(gi_arr)
        from karpenter_tpu.ops import native as nat

        # The native kernel's steady-state joins run without up-calls, so
        # they can't re-run the minValues diversity gate or the per-join
        # reservation bookkeeping — those solves take the instrumented
        # Python loop (identical semantics, rare catalog shapes)
        if nat.get_lib() is not None and not self.min_active and not self.res_active:
            pods_sorted = [self.pods[i] for i in order]
            driver = _NativeDriver(
                self, pods_sorted, np.ascontiguousarray(gi_arr[order]), timeout
            )
            self._native = driver
            try:
                driver.drive()
            finally:
                driver.close()
                self._native = None
            return
        qpods = [(self.pods[i], int(gi_arr[i])) for i in order]
        head = 0
        last_len: dict[str, int] = {}
        pod_errors = self.pod_errors
        start = time.perf_counter()
        check = 0
        while head < len(qpods):
            pod, gi = qpods[head]
            if last_len.get(pod.metadata.uid) == len(qpods) - head:
                break
            check += 1
            if timeout is not None and not (check & 0x1FF):
                if time.perf_counter() - start > timeout:
                    self.timed_out = True
                    for p, _ in qpods[head:]:
                        pod_errors.setdefault(
                            p, TimeoutError("scheduling simulation timed out")
                        )
                    return
            head += 1
            g = self.groups[gi]
            if self.nodes and self._try_nodes(pod, g, gi):
                pod_errors.pop(pod, None)
                continue
            if self._try_claims(pod, g, gi):
                pod_errors.pop(pod, None)
                continue
            if not self.s.nodeclaim_templates:
                err: Exception = ValueError(
                    "nodepool requirements filtered out all available instance types"
                )
            else:
                maybe = self._new_claim(pod, g, gi)
                if maybe is None:
                    pod_errors.pop(pod, None)
                    continue
                err = maybe
            pod_errors[pod] = err
            qpods.append((pod, gi))
            last_len[pod.metadata.uid] = len(qpods) - head

    # -- output --------------------------------------------------------------

    def emit(self):
        """Materialize scheduler state: existing-node fills, nodepool limit
        tracking, and host SchedNodeClaim objects (one per claim)."""
        import copy as _copy

        from karpenter_tpu.scheduler.nodeclaim import NodeClaim as SchedNodeClaim

        s = self.s
        # only touched wrappers can have joins; untouched nodes need no
        # materialization just to skip them
        for nd in self.nodes.materialized():
            if not nd.joined:
                continue
            en = nd.en
            en.pods.extend(nd.joined)
            en.remaining_resources = nd.remaining
            en.requirements = nd.reqs
        s.remaining_resources.update(self.remaining_resources)
        opt_index_arr = [np.asarray(idxs, dtype=np.int64) for idxs in self.opt_index]
        # an empty daemon HostPortUsage (the common case) needs no deepcopy
        empty_hostports = {
            nct: not s.daemon_hostports[nct] for nct in s.nodeclaim_templates
        }
        # claims sharing (template, surviving-type set) share one options
        # list — anti-affinity-heavy solves open thousands of identical
        # claims and the per-claim list build dominated emit. Downstream
        # only ever REASSIGNS instance_type_options, never mutates in place.
        options_cache: dict[tuple, list] = {}
        for ci, c in enumerate(self.claims):
            if c.defer is not None:
                self._materialize(c)
            nct = s.nodeclaim_templates[c.ti]
            tracked_hp = self._claim_hp.get(ci)
            surv_u = np.zeros(self.U, dtype=bool)
            surv_u[c.u_ids] = True
            final_types = c.type_mask & surv_u[self.uid_of_type]
            okey = (c.ti, final_types.tobytes())
            options = options_cache.get(okey)
            if options is None:
                tmpl_opts = self.tmpl_options[c.ti]
                options = [
                    tmpl_opts[j]
                    for j in np.nonzero(final_types[opt_index_arr[c.ti]])[0]
                ]
                options_cache[okey] = options
            fam_vals = self.fam_reqs[c.fam].values()
            if c.min_relaxed:
                # BestEffort wrote the claim's minValues down to the
                # achievable counts at open (nodeclaim.go:425-436). Family
                # Requirement objects are shared across claims — substitute
                # per-claim copies rather than mutating interned rows.
                # (Substitution, not add(): add() max-merges min_values.)
                relaxed_vals = dict(c.min_specs)
                out = []
                for r in fam_vals:
                    rv = relaxed_vals.get(r.key)
                    if (
                        rv is not None
                        and r.min_values is not None
                        and rv < r.min_values
                    ):
                        r = _copy.copy(r)
                        r.min_values = rv
                    out.append(r)
                fam_vals = out
            reqs = Requirements(*fam_vals)
            reqs.add(Requirement(wk.LABEL_HOSTNAME, Operator.IN, [c.hostname]))
            requests = dict(s.daemon_overhead[nct])
            for gi, count in c.group_counts.items():
                g = self.groups[gi]
                requests = res.merge(
                    requests, {k: v * count for k, v in g.requests.items()}
                )
            nc = SchedNodeClaim.from_precomputed(
                nct,
                s.topology,
                s.daemon_overhead[nct],
                tracked_hp
                if tracked_hp is not None
                else HostPortUsage()
                if empty_hostports[nct]
                else _copy.deepcopy(s.daemon_hostports[nct]),
                options,
                s.reservation_manager,
                s.reserved_offering_mode,
                s.reserved_capacity_enabled,
                s.engine,
                c.hostname,
                reqs,
                list(c.members),
                requests,
            )
            nc.annotations[wk.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY] = (
                "true" if c.min_relaxed else "false"
            )
            if self.res_active and c.reserved:
                # reservations were already applied to the shared manager at
                # join time; finalize_scheduling pins capacity-type +
                # reservation ids from this list (nodeclaim.go:207-220)
                nc.reserved_offerings = list(c.reserved)
            s.new_node_claims.append(nc)


def solve_device(scheduler, pods: Sequence[Pod], timeout: Optional[float] = 60.0):
    """Run the device-accelerated exact FFD; returns Results, or None → the
    caller uses the host loop (ineligible shape/solve)."""
    global DEVICE_SOLVES, DEVICE_FALLBACKS
    from karpenter_tpu.scheduler.scheduler import Results

    if not eligible(scheduler, pods):
        DEVICE_FALLBACKS += 1
        _FALLBACKS_CTR.inc()
        return None
    from karpenter_tpu.ops import ffd_topo

    if not ffd_topo.supported(scheduler):
        DEVICE_FALLBACKS += 1
        _FALLBACKS_CTR.inc()
        return None
    from karpenter_tpu.ops import fused as fused_mod

    topo = scheduler.topology
    strict_reserved = _strict_reserved(scheduler)
    if (
        getattr(topo, "topology_groups", None)
        or getattr(topo, "inverse_topology_groups", None)
        # PreferNoSchedule pools: every pod may relax via the wildcard
        # toleration rung — only the topo driver drives the relax ladder
        or scheduler.preferences.tolerate_prefer_no_schedule
        # strict reserved mode: reservation exhaustion rejects candidates
        # non-monotonically and aborts pod scans — volatile paths only
        or strict_reserved
    ):
        attempts = [ffd_topo._TopoSolve]
        if fused_mod.fused_enabled():
            # the fused scan never drives the relax ladder / volatile paths
            fused_mod.note_decline("topo")
    else:
        # fused one-dispatch scan first (when enabled), then the plain
        # driver (native kernel); shapes it declines that only need the
        # relax ladder (preferred/multi-term node affinity) retry on the
        # topo driver, which relaxes exactly like the host
        attempts = list(fused_mod.maybe_attempts(scheduler)) + [
            _DeviceSolve,
            ffd_topo._TopoSolve,
        ]
    done = False
    for idx, cls in enumerate(attempts):
        last = idx == len(attempts) - 1
        solve = None
        try:
            solve = cls(scheduler, pods)
            solve.run(timeout)
            solve.emit()
            done = True
            break
        except fused_mod._FusedDecline:
            # not scan-shaped — the host-walk drivers are the designed slow
            # path (the decline is already metered by taxonomy reason)
            solve.abort()
            if not last:
                continue
            break
        except _IneligibleShape:
            solve.abort()
            if not last:
                continue
            break
        except _Fallback:
            solve.abort()
            break
        except Exception:
            if solve is not None:
                solve.abort()
            if STRICT:
                raise
            break
    if not done:
        DEVICE_FALLBACKS += 1
        _FALLBACKS_CTR.inc()
        return None
    DEVICE_SOLVES += 1
    _SOLVES_CTR.inc()
    for nc in scheduler.new_node_claims:
        nc.finalize_scheduling()
    return Results(
        new_node_claims=scheduler.new_node_claims,
        existing_nodes=scheduler.existing_nodes,
        pod_errors=solve.pod_errors,
        timed_out=solve.timed_out,
    )


# -- solverd coalescing hooks -------------------------------------------------


def collect_joint_rowsets(scheduler, pods: Sequence[Pod]) -> list[tuple]:
    """Enumerate the joint (template x group) requirement row-sets a device
    solve of `pods` would sweep, WITHOUT dispatching the sweep. Pure host
    work: grouping plus requirement algebra, all of it shared with the
    subsequent real solve through the scheduler/engine caches.

    Returns [(rows_frozenset, joint Requirements)] for pairs not yet in the
    engine's joint cache, or [] when the solve wouldn't take the device path
    (ineligible shape, tiny batch, degenerate shape count). solverd's
    coalescer unions these across concurrent requests so several solves
    share ONE batched device sweep (prime_joint_masks)."""
    if scheduler.engine is None or not eligible(scheduler, pods):
        return []
    try:
        solve = _DeviceSolve(scheduler, pods)
        if solve._group_pods() is None:
            return []
        pairs = solve._joint_pairs()
        if pairs is None:
            # degenerate shape counts evaluate joints lazily per pair
            # (_prepare_templates): there is no sweep to coalesce
            return []
        return [
            (rows, joint)
            for rows, joint in pairs
            if rows not in solve.joint_cache
        ]
    except Exception:  # noqa: BLE001 — priming is best-effort, never fatal
        return []


def collect_prefix_rowsets(schedulers_pods: Sequence[tuple]) -> list[tuple]:
    """Prefix-mask variant of collect_joint_rowsets for frontier groups:
    the k solves of a consolidation frontier round simulate nested prefixes
    of one candidate order, so their pod sets nest — every shape group (and
    therefore every joint (template x group) row-set) of a smaller prefix
    appears in the largest one. Collecting from the largest member alone
    yields the union the per-member loop would, for one prefix's worth of
    grouping work, and the single prime_joint_masks sweep that follows is
    the one feasibility pass all k prefixes share. Under-collection is
    impossible for nested inputs and harmless otherwise: priming only warms
    the joint cache — a solve whose pair wasn't primed computes it exactly,
    host-side, on demand."""
    if not schedulers_pods:
        return []
    scheduler, pods = max(schedulers_pods, key=lambda sp: len(sp[1]))
    return collect_joint_rowsets(scheduler, pods)


def prime_joint_masks(engine: "CatalogEngine", pairs: Sequence[tuple]) -> int:
    """Fill `engine.solver_joint_cache` for the given (rows, joint
    Requirements) pairs in ONE batched device sweep; solves that follow find
    their masks warm and dispatch nothing. Returns the number of fresh
    entries primed (0 → no device call was made).

    On sweep failure the reserved None placeholders stay behind — exact but
    slower: _joint_masks computes those entries host-side on demand."""
    global JOINT_SWEEPS
    fresh_rows: list[frozenset] = []
    fresh_reqs: list[Requirements] = []
    for rows, reqs in pairs:
        if rows in engine.solver_joint_cache:
            continue
        engine.solver_joint_cache[rows] = None  # reserve
        fresh_rows.append(rows)
        fresh_reqs.append(reqs)
    if not fresh_rows:
        return 0
    requests = np.zeros(
        (len(fresh_rows), len(engine.resource_dims)), dtype=np.float32
    )
    fz = engine.feasibility(
        [list(rows) for rows in fresh_rows],
        requests,
        engine.key_presence(fresh_reqs),
    )
    JOINT_SWEEPS += 1
    _JOINT_SWEEPS_CTR.inc()
    for i, rows in enumerate(fresh_rows):
        # copy: these persist on the engine; a row VIEW would pin the whole
        # padded sweep matrix alive (same rationale as _prepare_templates)
        engine.solver_joint_cache[rows] = (
            fz.compat[i].copy(),
            fz.has_offering[i].copy(),
        )
    return len(fresh_rows)
