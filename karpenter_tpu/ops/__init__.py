"""Device kernels: the TPU-native execution backend for the two solvers.

Design (see SURVEY.md §7): label keys and values are interned into a global
bit-space; every distinct `Requirement` becomes one row of arrays; the hot
`filterInstanceTypesByRequirements` sweep (reference
pkg/controllers/provisioning/scheduling/nodeclaim.go:373-441) becomes

    compat[P, I] = all-over-pod-requirements ReqCompat[R, I]

computed as a membership matmul — MXU-shaped — instead of the reference's
O(pods × instance-types × keys) Go loops.
"""
