"""Host↔device encoding: intern label keys/values, flatten Requirement sets
into fixed-shape arrays.

The vocabulary assigns every (key, value) pair a slot in a single global
bit-space so a requirement's explicit value set is one packed uint32 bitmask.
Per-key metadata (present / complement / bounds) lives in dense [N, K] arrays.
Shapes are padded to power-of-two capacities so XLA compile caches hit as the
vocabulary grows (SURVEY.md §7 "bucketing/padding discipline").

Semantic source: reference pkg/scheduling/requirement.go:33-350 (complement
sets, integer bounds, open-world NotIn/Exists).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

import numpy as np

from karpenter_tpu.scheduling.requirements import Requirement, Requirements

WORD = 32
INT32_MIN = -(2**31)
INT32_MAX = 2**31 - 1
# Sentinels for "no bound": gt=INT32_MIN means no lower bound, lt=INT32_MAX none.
NO_GT = INT32_MIN
NO_LT = INT32_MAX
# value_int sentinel for non-integer values
NOT_INT = INT32_MIN


def _next_pow2(n: int, floor: int = 8) -> int:
    c = floor
    while c < n:
        c *= 2
    return c


@dataclass
class Vocab:
    """Interning table for label keys and per-key values.

    Every value of every key occupies one slot in a global bit-space
    [0, num_slots). Slots for one key are NOT necessarily contiguous (values
    are appended as discovered); per-key membership is tracked by
    `slot_key[slot] = key_id`, and masks for different keys never overlap,
    so whole-bitmask AND/OR ops are safe without per-key segmenting.
    """

    key_ids: dict[str, int] = field(default_factory=dict)
    keys: list[str] = field(default_factory=list)
    # (key_id, value) -> global slot
    slot_ids: dict[tuple[int, str], int] = field(default_factory=dict)
    slot_key: list[int] = field(default_factory=list)
    slot_value_int: list[int] = field(default_factory=list)
    _version: int = 0

    @property
    def num_keys(self) -> int:
        return len(self.keys)

    @property
    def num_slots(self) -> int:
        return len(self.slot_key)

    @property
    def version(self) -> int:
        """Bumped whenever the vocabulary grows (invalidates device tables)."""
        return self._version

    def key_id(self, key: str) -> int:
        kid = self.key_ids.get(key)
        if kid is None:
            kid = len(self.keys)
            self.key_ids[key] = kid
            self.keys.append(key)
            self._version += 1
        return kid

    def slot(self, key: str, value: str) -> int:
        kid = self.key_id(key)
        sid = self.slot_ids.get((kid, value))
        if sid is None:
            sid = len(self.slot_key)
            self.slot_ids[(kid, value)] = sid
            self.slot_key.append(kid)
            try:
                iv = int(value)
                if not (INT32_MIN < iv < INT32_MAX):
                    iv = NOT_INT
            except ValueError:
                iv = NOT_INT
            self.slot_value_int.append(iv)
            self._version += 1
        return sid

    def observe(self, reqs: Requirements) -> None:
        """Intern every key/value in a requirement set."""
        for r in reqs:
            kid = self.key_id(r.key)
            for v in r.values:
                self.slot(r.key, v)

    # -- capacities (padded for stable compiled shapes) ---------------------

    @property
    def key_capacity(self) -> int:
        return _next_pow2(self.num_keys, 8)

    @property
    def word_capacity(self) -> int:
        return _next_pow2((self.num_slots + WORD - 1) // WORD, 2)

    def tables(self) -> "VocabTables":
        """Dense numpy tables for device-side per-slot metadata."""
        w = self.word_capacity
        g = w * WORD
        slot_key = np.full((g,), -1, dtype=np.int32)
        slot_key[: self.num_slots] = np.asarray(self.slot_key, dtype=np.int32)
        value_int = np.full((g,), NOT_INT, dtype=np.int32)
        value_int[: self.num_slots] = np.asarray(self.slot_value_int, dtype=np.int32)
        return VocabTables(slot_key=slot_key, value_int=value_int, num_slots=self.num_slots)


@dataclass
class VocabTables:
    slot_key: np.ndarray  # [G] int32: owning key id per slot (-1 = unused)
    value_int: np.ndarray  # [G] int32: integer value or NOT_INT
    num_slots: int


@dataclass
class EncodedReqs:
    """N requirement rows as arrays.

    A row is one `Requirement` (single key). Requirement *sets* are
    represented as groups of rows via external membership indices.
    """

    key: np.ndarray  # [N] int32 key id
    complement: np.ndarray  # [N] bool
    has_values: np.ndarray  # [N] bool (len(values) > 0)
    gt: np.ndarray  # [N] int32 (NO_GT when unset)
    lt: np.ndarray  # [N] int32 (NO_LT when unset)
    mask: np.ndarray  # [N, W] uint32 packed explicit-value bitmask

    def __len__(self) -> int:
        return self.key.shape[0]


def requirements_fingerprint(reqs: Requirements) -> bytes:
    """Canonical content fingerprint of a requirement set: two sets with
    the same semantics — same keys, complement flags, value sets, integer
    bounds, min_values — hash identically regardless of object identity or
    construction order. The incremental encode cache (ops/delta.py) keys
    its cross-pass row cache on this, so churn that REBUILDS a workload's
    Requirements every pass (watch events re-decode pod specs into fresh
    objects) still reuses the interned rows instead of re-encoding."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for r in sorted(reqs, key=lambda r: r.key):
        h.update(r.key.encode())
        h.update(b"\x01" if r.complement else b"\x00")
        for v in sorted(r.values):
            h.update(b"\x1f")
            h.update(v.encode())
        h.update(b"\x1e")
        h.update(str(getattr(r, "greater_than", None)).encode())
        h.update(str(getattr(r, "less_than", None)).encode())
        h.update(str(getattr(r, "min_values", None)).encode())
    return h.digest()


def encode_requirement_rows(
    vocab: Vocab, rows: Sequence[Requirement], word_capacity: Optional[int] = None
) -> EncodedReqs:
    """Encode individual requirements as rows.

    Interns every key/value first so the word capacity is final before the
    mask array is sized; raises if a caller-pinned capacity is outgrown.
    """
    n = len(rows)
    for row in rows:
        vocab.key_id(row.key)
        for v in row.values:
            vocab.slot(row.key, v)
    if word_capacity is not None and word_capacity < vocab.word_capacity:
        raise ValueError("vocabulary grew past the provided word capacity")
    w = word_capacity or vocab.word_capacity
    key = np.zeros((n,), dtype=np.int32)
    complement = np.zeros((n,), dtype=bool)
    has_values = np.zeros((n,), dtype=bool)
    gt = np.full((n,), NO_GT, dtype=np.int32)
    lt = np.full((n,), NO_LT, dtype=np.int32)
    mask = np.zeros((n, w), dtype=np.uint32)
    for i, r in enumerate(rows):
        key[i] = vocab.key_id(r.key)
        complement[i] = r.complement
        has_values[i] = bool(r.values)
        if r.greater_than is not None:
            gt[i] = r.greater_than
        if r.less_than is not None:
            lt[i] = r.less_than
        for v in r.values:
            s = vocab.slot(r.key, v)
            mask[i, s // WORD] |= np.uint32(1 << (s % WORD))
    return EncodedReqs(key, complement, has_values, gt, lt, mask)


@dataclass
class EncodedReqSets:
    """N requirement *sets*, each a per-key row in dense [N, K] layout.

    Used for entities whose full key map matters (instance types, offerings):
    per key we store whether the set constrains it and how.
    """

    present: np.ndarray  # [N, K] bool
    complement: np.ndarray  # [N, K] bool
    has_values: np.ndarray  # [N, K] bool
    gt: np.ndarray  # [N, K] int32
    lt: np.ndarray  # [N, K] int32
    mask: np.ndarray  # [N, W] uint32 — union over keys; keys don't share slots

    def __len__(self) -> int:
        return self.present.shape[0]


def encode_requirement_sets(
    vocab: Vocab,
    sets: Sequence[Requirements],
    key_capacity: Optional[int] = None,
    word_capacity: Optional[int] = None,
) -> EncodedReqSets:
    """Encode requirement sets into dense per-key arrays. Interns first so
    capacities are final before allocation."""
    for rs in sets:
        vocab.observe(rs)
    n = len(sets)
    k = key_capacity or vocab.key_capacity
    w = word_capacity or vocab.word_capacity
    if k < vocab.key_capacity or w < vocab.word_capacity:
        raise ValueError("provided capacities too small for vocabulary")
    present = np.zeros((n, k), dtype=bool)
    complement = np.zeros((n, k), dtype=bool)
    has_values = np.zeros((n, k), dtype=bool)
    gt = np.full((n, k), NO_GT, dtype=np.int32)
    lt = np.full((n, k), NO_LT, dtype=np.int32)
    mask = np.zeros((n, w), dtype=np.uint32)
    for i, rs in enumerate(sets):
        for r in rs:
            kid = vocab.key_id(r.key)
            present[i, kid] = True
            complement[i, kid] = r.complement
            has_values[i, kid] = bool(r.values)
            if r.greater_than is not None:
                gt[i, kid] = r.greater_than
            if r.less_than is not None:
                lt[i, kid] = r.less_than
            for v in r.values:
                s = vocab.slot(r.key, v)
                mask[i, s // WORD] |= np.uint32(1 << (s % WORD))
    return EncodedReqSets(present, complement, has_values, gt, lt, mask)


@dataclass
class DomainVocab:
    """Interning table for topology DOMAIN strings (zone names, hostnames,
    custom-key values): one dense id-space per topology group, so the
    group's occupancy lives in a count vector indexed by domain id instead
    of a str-keyed dict (ops/topo_counts.py). Ids are append-only — a
    domain keeps its slot for the vocabulary's lifetime, so count tensors
    survive re-syncs without re-indexing."""

    ids: dict[str, int] = field(default_factory=dict)
    domains: list[str] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.domains)

    def id(self, domain: str) -> int:
        """Interned id for `domain`, assigning the next slot on first use."""
        did = self.ids.get(domain)
        if did is None:
            did = len(self.domains)
            self.ids[domain] = did
            self.domains.append(domain)
        return did

    def lookup(self, domain: str) -> Optional[int]:
        """Id for `domain` without interning (None when never seen)."""
        return self.ids.get(domain)


def encode_resource_dims(resource_names: Sequence[str]) -> dict[str, int]:
    return {name: i for i, name in enumerate(resource_names)}


def encode_resource_lists(
    dims: dict[str, int], items: Sequence[dict], missing: float = 0.0
) -> np.ndarray:
    """[N, R] float64 resource matrix; unknown resource names must be
    registered in `dims` by the caller beforehand. float64 so byte-scale
    memory values stay exact — the device packer quantizes separately
    (feasibility.quantize_resources)."""
    out = np.full((len(items), len(dims)), missing, dtype=np.float64)
    for i, rl in enumerate(items):
        for name, v in rl.items():
            out[i, dims[name]] = v
    return out
