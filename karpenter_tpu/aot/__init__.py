"""aot: the ahead-of-time compile service.

Makes XLA compilation a managed, persistent artifact instead of a lazy
side effect. Three pieces (ROADMAP item 2):

- a **bucket ladder** (aot/ladder.py): a fixed, versioned set of padded
  shape buckets per kernel; runtime dispatches pad to ladder buckets, and
  a dispatch that misses the ladder is a warning event + counter
- an **AOT compiler** (aot/compiler.py): walks the ladder at boot via
  ``jit(...).lower().compile()``, backed by a **persistent executable
  cache** (aot/cache.py) keyed by (catalog content hash, jax/XLA version,
  device kind, bucket, ladder version) with corruption-safe load
- a **warm-start path**: provisioner.prewarm() and the solverd daemon's
  engine factory call ``warm_start``; the runtime executable table
  (aot/runtime.py) serves prepaid executables to every named dispatch

This module stays import-light (no jax); the compiler loads lazily.
"""

from karpenter_tpu.aot import ladder, runtime  # noqa: F401
from karpenter_tpu.aot.cache import ExecutableCache  # noqa: F401
from karpenter_tpu.aot.ladder import LADDER_VERSION, Ladder  # noqa: F401


def warm_start(engine, **kwargs):
    """Load-or-compile the ladder's executables for `engine`; see
    aot/compiler.warm_start."""
    from karpenter_tpu.aot import compiler

    return compiler.warm_start(engine, **kwargs)


def configure_from_options(options) -> None:
    runtime.configure_from_options(options)
