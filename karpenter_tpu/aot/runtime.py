"""AOT runtime state: the executable table, active ladder/cache config,
and off-ladder accounting.

The executable table maps (kernel name, full shape signature) to a loaded
XLA executable. `tracing/kernel.dispatch` consults it on every named
dispatch: a hit executes the AOT executable directly — no trace, no jit
cache, no compile — which is what makes a warm-started daemon's first solve
run entirely on prepaid executables. Signatures embed every array dim
(catalog dims included), so executables built for one catalog can never
serve another.

Off-ladder accounting: a device dispatch of a laddered kernel whose dims
exceed every configured bucket is counted
(``karpenter_aot_offladder_dispatches_total{kernel=}``), logged once per
(kernel, shape), and fired at registered callbacks — the provisioner
publishes an ``AOTOffLadderDispatch`` warning event. Off-ladder dispatches
still execute correctly (plain power-of-two padding, a fresh jit compile);
the warning is the ladder-tuning signal, and
``/debug/kernels?view=ladder`` is its drill-down.

This module must stay import-light (no jax): it is imported by the
dispatch hot path and by the observability layer.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from karpenter_tpu.metrics import global_registry
from karpenter_tpu.operator import logging as klog

from karpenter_tpu.aot import ladder as ladder_mod
from karpenter_tpu.aot.cache import ExecutableCache

_log = klog.logger("aot")

_OFF_LADDER = global_registry.counter(
    "karpenter_aot_offladder_dispatches_total",
    "device dispatches of laddered kernels whose shape missed every "
    "configured AOT bucket (each one jit-compiles a shape the warm start "
    "never prepaid); mesh labels the device layout of sharded dispatches "
    "('' = unsharded)",
    labels=["kernel", "mesh"],
)
_EXEC_FALLBACKS = global_registry.counter(
    "karpenter_aot_executable_fallbacks_total",
    "AOT executable invocations that failed and fell back to JIT",
    labels=["kernel"],
)

_lock = threading.Lock()
_LADDER: Optional[ladder_mod.Ladder] = None
_CACHE: Optional[ExecutableCache] = None
_EXECUTABLES: dict[tuple, object] = {}
_OFF_LADDER_EVENTS: list[dict] = []
_OFF_LADDER_COUNT = 0
_OFF_LADDER_SEEN: set[tuple] = set()
_OFF_LADDER_CBS: dict[str, Callable[[str, str], None]] = {}
_FRESH_COMPILES = 0
_WARM_STARTS = 0


# -- configuration ------------------------------------------------------------


def configure(
    ladder: Optional[ladder_mod.Ladder], cache: Optional[ExecutableCache]
) -> None:
    """Install the process's active ladder + cache (None/None disables AOT).
    Executables already loaded stay installed — they are keyed by full
    shape signature and remain correct regardless of configuration."""
    global _LADDER, _CACHE
    with _lock:
        _LADDER = ladder
        _CACHE = cache


def configure_from_options(options) -> None:
    """Operator/daemon boot: resolve --aot-ladder / --compile-cache-dir.
    A cache dir with no explicit ladder implies the default ladder (a
    persistent cache is pointless without buckets to fill it with)."""
    spec = getattr(options, "aot_ladder", "") or ""
    cache_dir = getattr(options, "compile_cache_dir", "") or ""
    if not spec and cache_dir:
        spec = "default"
    ladder = ladder_mod.resolve(spec)
    cache = ExecutableCache(cache_dir) if (ladder and cache_dir) else None
    configure(ladder, cache)


def enabled() -> bool:
    return _LADDER is not None


def active_ladder() -> Optional[ladder_mod.Ladder]:
    return _LADDER


def active_cache() -> Optional[ExecutableCache]:
    return _CACHE


# -- the executable table -----------------------------------------------------


def lookup(kernel: Optional[str], sig: Optional[str], scope: str = ""):
    """`scope` separates executables that share a (kernel, shape) identity
    but were compiled for different device layouts — a shard_mapped kernel's
    global shape is mesh-size-invariant by design (ladder.MESH_ALIGN), so
    the mesh shape must live in the TABLE key, never in the observatory's
    shape signature (kernel digests stay mesh-invariant)."""
    if kernel is None or not _EXECUTABLES:
        return None
    return _EXECUTABLES.get((kernel, sig, scope))


def install(kernel: str, sig: str, executable, scope: str = "") -> None:
    with _lock:
        _EXECUTABLES[(kernel, sig, scope)] = executable


def discard(
    kernel: str, sig: str, error: Optional[str] = None, scope: str = ""
) -> None:
    """An installed executable failed at call time (backend change, aval
    drift): drop it and count the fallback — dispatch re-runs through jit."""
    with _lock:
        _EXECUTABLES.pop((kernel, sig, scope), None)
    _EXEC_FALLBACKS.inc({"kernel": kernel})
    _log.warning(
        "AOT executable failed; falling back to JIT",
        kernel=kernel, shape=sig, scope=scope or None, error=error or "",
    )


def executables() -> list[dict]:
    with _lock:
        return [
            {"kernel": k, "shape": s, **({"scope": sc} if sc else {})}
            for (k, s, sc) in sorted(_EXECUTABLES)
        ]


def clear_executables() -> None:
    """Tests and restart legs: forget every loaded executable."""
    with _lock:
        _EXECUTABLES.clear()


def note_warm_start(fresh_compiles: int) -> None:
    global _FRESH_COMPILES, _WARM_STARTS
    with _lock:
        _FRESH_COMPILES += fresh_compiles
        _WARM_STARTS += 1


# -- off-ladder accounting ----------------------------------------------------


def on_off_ladder(cb: Callable[[str, str], None], key: str = "default") -> None:
    """Register a (kernel, shape) callback for off-ladder dispatches. Keyed
    replace semantics, like KernelRegistry.on_recompile."""
    with _lock:
        _OFF_LADDER_CBS[key] = cb


def note_off_ladder(kernel: str, shape: str, mesh: str = "") -> None:
    """`mesh` carries the device layout of a sharded dispatch (e.g.
    "mesh=8:pods"): it labels the counter and the event so a mis-sized
    ladder's warnings name WHICH mesh shape missed, not just the kernel."""
    global _OFF_LADDER_COUNT
    with _lock:
        _OFF_LADDER_COUNT += 1
        event = {"kernel": kernel, "shape": shape}
        if mesh:
            event["mesh"] = mesh
        _OFF_LADDER_EVENTS.append(event)
        del _OFF_LADDER_EVENTS[:-50]
        first = (kernel, shape, mesh) not in _OFF_LADDER_SEEN
        _OFF_LADDER_SEEN.add((kernel, shape, mesh))
        cbs = tuple(_OFF_LADDER_CBS.values())
    _OFF_LADDER.inc({"kernel": kernel, "mesh": mesh})
    if first:
        _log.warning(
            "dispatch missed the AOT bucket ladder; this shape jit-compiles "
            "instead of warm-starting — tune the ladder "
            "(/debug/kernels?view=ladder)",
            kernel=kernel, shape=shape, mesh=mesh or None,
        )
    # callbacks keep the 2-arg (kernel, shape) contract; a sharded
    # dispatch's shape carries the mesh so the published event names it
    cb_shape = f"{shape}@{mesh}" if mesh else shape
    for cb in cbs:
        try:
            cb(kernel, cb_shape)
        except Exception:  # noqa: BLE001 — observers never break dispatch
            pass


def reset_off_ladder() -> None:
    """Tests only."""
    global _OFF_LADDER_COUNT
    with _lock:
        _OFF_LADDER_COUNT = 0
        _OFF_LADDER_EVENTS.clear()
        _OFF_LADDER_SEEN.clear()
        _OFF_LADDER_CBS.clear()


# -- introspection ------------------------------------------------------------


def stats() -> dict:
    """Cumulative AOT state: cache traffic, loaded executables, off-ladder
    count. The sim snapshots this at run start and reports the delta.
    Cache traffic reads the PROCESS totals (aot/cache.totals), not the
    active instance, so deltas stay monotonic across re-configures."""
    from karpenter_tpu.aot import cache as cache_mod

    cache_stats = cache_mod.totals()
    with _lock:
        return {
            "enabled": _LADDER is not None,
            "ladder_version": _LADDER.version if _LADDER else None,
            "executables_loaded": len(_EXECUTABLES),
            "warm_starts": _WARM_STARTS,
            "fresh_compiles": _FRESH_COMPILES,
            "off_ladder_dispatches": _OFF_LADDER_COUNT,
            "cache_hits": cache_stats["hits"],
            "cache_misses": cache_stats["misses"],
            "cache_evictions": cache_stats["evictions"],
            "cache_write_errors": cache_stats["write_errors"],
        }


_DELTA_KEYS = (
    "warm_starts",
    "fresh_compiles",
    "off_ladder_dispatches",
    "cache_hits",
    "cache_misses",
    "cache_evictions",
    "cache_write_errors",
)


def stats_delta(base: dict) -> dict:
    now = stats()
    out = {
        k: v for k, v in now.items() if k not in _DELTA_KEYS
    }
    for k in _DELTA_KEYS:
        out[k] = now[k] - base.get(k, 0)
    return out


def ladder_view() -> dict:
    """/debug/kernels?view=ladder: the configured ladder next to the
    observatory's observed shape buckets, flagging off-ladder dispatches —
    the drill-down data for tuning the ladder."""
    from karpenter_tpu.observability import kernels as kobs

    ladder = _LADDER
    snap = kobs.registry().counts_snapshot()
    observed: dict[str, list] = {}
    with _lock:
        # on_ladder is a (kernel, shape) question — any scope's executable
        # (a mesh variant included) makes the observed bucket prepaid
        installed = {(k, s) for (k, s, _scope) in _EXECUTABLES}
        off_events = list(_OFF_LADDER_EVENTS)
        off_count = _OFF_LADDER_COUNT
    for name in sorted(snap):
        rows = []
        for shape, phases in sorted(snap[name]["shapes"].items()):
            device = bool(
                phases.get("warmup") or phases.get("steady")
                or phases.get("aot-warm")
            )
            row = {
                "shape": shape,
                "phases": {k: v for k, v in phases.items() if v},
            }
            if device and ladder is not None and name in ladder.kernels:
                row["on_ladder"] = (name, shape) in installed
            rows.append(row)
        observed[name] = rows
    return {
        "enabled": ladder is not None,
        "ladder_version": ladder.version if ladder else None,
        "ladder": ladder.to_dict()["kernels"] if ladder else {},
        "executables": executables(),
        "off_ladder": {"count": off_count, "events": off_events},
        "observed": observed,
        "cache": _CACHE.stats() if _CACHE is not None else None,
    }
