"""AOT compiler: walk the bucket ladder at boot, load or compile each
executable, persist fresh compiles to the on-disk cache.

``warm_start(engine)`` is the managed replacement for the lazy
``CatalogEngine.warmup()`` cold path: it attaches the active ladder to the
engine (so runtime dispatches pad to ladder buckets), stabilizes the
vocabulary's key capacity (pre-interning the well-known label keys pods
constrain with, so the padded key axis at boot equals the steady-state
one), then for every (kernel, bucket) in the ladder either

- loads a serialized executable from the persistent cache
  (``deserialize_and_load`` — milliseconds), or
- compiles it ahead of time (``jit(...).lower(*abstract).compile()``) and
  serializes it into the cache for the next boot,

installing each into the runtime executable table that
``tracing/kernel.dispatch`` consults. Every bucket is recorded into the
kernel observatory under the ``aot-warm`` phase, with ``compiled=True``
only for fresh compiles — which is exactly what the warm-boot perf floor
asserts is zero on a second boot against a warm cache.

Cache keys embed the catalog content hash (the same fingerprint solverd
content-addresses engines by), the jax/jaxlib versions, the backend +
device kind, the kernel, the bucket signature, and the ladder version —
any mismatch is a miss, so a version bump or a device swap can never load
a stale executable. Corrupt entries evict and fall back to a fresh
compile; nothing in this path is allowed to crash a boot.
"""

from __future__ import annotations

import hashlib
import pickle
import time
from typing import Optional

import numpy as np

from karpenter_tpu.observability import kernels as kobs
from karpenter_tpu.operator import logging as klog

from karpenter_tpu.aot import ladder as ladder_mod
from karpenter_tpu.aot import runtime as aotrt
from karpenter_tpu.aot.cache import ExecutableCache

_log = klog.logger("aot")


def content_hash(instance_types) -> str:
    """The catalog content fingerprint — the same identity solverd's
    engine factories content-address engines by (provisioner
    _type_fingerprint), hashed for the cache key."""
    from karpenter_tpu.controllers.provisioning.provisioner import (
        _type_fingerprint,
    )

    fp = tuple(_type_fingerprint(it) for it in instance_types)
    return hashlib.sha256(repr(fp).encode()).hexdigest()


def _toolchain_fingerprint() -> str:
    import jax

    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "?")
    except Exception:  # noqa: BLE001 — jaxlib version is advisory
        jl = "?"
    try:
        backend = jax.default_backend()
        kind = getattr(jax.devices()[0], "device_kind", "?")
    except Exception:  # noqa: BLE001 — no usable backend
        backend, kind = "none", "?"
    return f"jax={jax.__version__};jaxlib={jl};backend={backend};device={kind}"


def cache_key(
    catalog_hash: str, kernel: str, sig: str, ladder_version: int,
    scope: str = "", donation: str = "",
) -> str:
    """`scope` folds the device layout of a sharded executable into its
    identity (ops/feasibility.mesh_scope) — sharded global shapes are
    mesh-size-invariant by design, so without the scope an executable
    compiled for an 8-way mesh could load into a 1-device process. An
    empty scope (every unsharded kernel) contributes NOTHING to the key,
    so persistent caches filled by pre-mesh builds stay valid.

    `donation` folds a kernel's buffer-donation signature into its
    identity (packer.SCAN_RESUME_DONATE for the delta warm resume):
    input-output aliasing is baked into the compiled executable, so a
    cache entry serialized with donation must never load into a
    non-donating call site or vice versa. Like scope, empty contributes
    nothing — pre-delta caches stay valid."""
    fields = [
        catalog_hash,
        _toolchain_fingerprint(),
        kernel,
        sig,
        f"ladder-v{ladder_version}",
    ]
    if scope:
        fields.append(scope)
    if donation:
        fields.append(donation)
    return hashlib.sha256("\n".join(fields).encode()).hexdigest()


# -- abstract-shape builders --------------------------------------------------


def _sds(shape, dtype, sharding=None):
    import jax

    return jax.ShapeDtypeStruct(
        tuple(int(d) for d in shape), np.dtype(dtype), sharding=sharding
    )


def _sig(args) -> str:
    return kobs.shape_signature(args)


def _cube_plans(engine, ladder: ladder_mod.Ladder) -> list[tuple]:
    """(kernel, fn, abstract args, sig) per feasibility bucket. The engine
    routes through production_cube when it has offerings, membership_all
    when it has none — mirror that so only reachable executables build."""
    from karpenter_tpu.ops import feasibility as feas

    I, O, K = engine.num_instances, engine.num_offerings, engine._key_capacity
    b = np.bool_
    plans = []
    if O:
        for P, R in ladder.buckets("feasibility.cube"):
            args = (
                _sds((P, R), b),
                _sds((R, I), b),
                _sds((R, O), b),
                _sds((O, K), b),
                _sds((P, K), b),
                _sds((O,), b),
                _sds((O, I), b),
            )
            plans.append(
                ("feasibility.cube", feas.production_cube, args, _sig(args))
            )
    else:
        for P, R in ladder.buckets("feasibility.membership"):
            args = (_sds((P, R), b), _sds((R, I), b))
            plans.append(
                ("feasibility.membership", feas.membership_all, args, _sig(args))
            )
    return plans


def _row_compat_plans(engine, ladder: ladder_mod.Ladder) -> list[tuple]:
    """Row-kernel buckets, one executable per (row bucket, target set):
    instance sets and (when present) offering sets have distinct N dims."""
    from karpenter_tpu.ops import feasibility as feas

    K, W = engine._key_capacity, engine._word_capacity
    G = W * 32
    b, i32, u32 = np.bool_, np.int32, np.uint32
    targets = [engine.num_instances]
    if engine.num_offerings:
        targets.append(engine.num_offerings)
    plans = []
    seen = set()
    for (R,) in ladder.buckets("catalog.row_compat"):
        for N in targets:
            args = (
                _sds((R,), i32),
                _sds((R,), b),
                _sds((R,), b),
                _sds((R,), i32),
                _sds((R,), i32),
                _sds((R, W), u32),
                _sds((N, K), b),
                _sds((N, K), b),
                _sds((N, K), b),
                _sds((N, K), i32),
                _sds((N, K), i32),
                _sds((N, W), u32),
                _sds((G,), i32),
                _sds((G,), i32),
            )
            sig = _sig(args)
            if sig in seen:
                continue
            seen.add(sig)
            plans.append(
                ("catalog.row_compat", feas.req_rows_vs_sets, args, sig)
            )
    return plans


def _mesh_shardings(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    axis = mesh.axis_names[0]
    return NamedSharding(mesh, PartitionSpec(axis)), NamedSharding(
        mesh, PartitionSpec()
    )


def _sharded_cube_plans(engine, ladder: ladder_mod.Ladder) -> list[tuple]:
    """Mesh twins of the cube plans: global bucket shapes with the serving
    path's exact input layout (entity axes sharded over the mesh, catalog
    matrices replicated). Only buckets the mesh splits evenly compile —
    the others are unreachable by construction (bucket_for multiple_of)."""
    from karpenter_tpu.ops import feasibility as feas

    mesh = engine.mesh
    n = int(np.prod(mesh.devices.shape))
    shard, rep = _mesh_shardings(mesh)
    scope = feas.mesh_scope(mesh)
    I, O, K = engine.num_instances, engine.num_offerings, engine._key_capacity
    b = np.bool_
    plans = []
    for P, R in ladder.buckets("feasibility.cube_sharded"):
        if P % n:
            continue
        args = (
            _sds((P, R), b, shard),
            _sds((R, I), b, rep),
            _sds((R, O), b, rep),
            _sds((O, K), b, rep),
            _sds((P, K), b, shard),
            _sds((O,), b, rep),
            _sds((O, I), b, rep),
        )
        plans.append(
            (
                "feasibility.cube_sharded",
                feas.sharded_cube(mesh),
                args,
                _sig(args),
                scope,
            )
        )
    return plans


def _sharded_solve_block_plans(engine, ladder: ladder_mod.Ladder) -> list[tuple]:
    """Mesh twins of the packer plans (group axis sharded, catalog
    replicated), compiled through the SAME jitted shard_map wrapper the
    serving path dispatches (packer.sharded_solve_block)."""
    from karpenter_tpu.ops import feasibility as feas
    from karpenter_tpu.ops import packer

    mesh = engine.mesh
    n = int(np.prod(mesh.devices.shape))
    shard, rep = _mesh_shardings(mesh)
    scope = feas.mesh_scope(mesh)
    I, O, K = engine.num_instances, engine.num_offerings, engine._key_capacity
    R = max(1, engine._computed_rows)
    D = len(engine.resource_dims)
    b, i32, f32 = np.bool_, np.int32, np.float32
    fn = packer.sharded_solve_block(mesh)
    plans = []
    for (G,) in ladder.buckets("packer.solve_block_sharded"):
        if G % n:
            continue
        args = (
            _sds((G, R + K), b, shard),
            _sds((G, D + 1), i32, shard),
            _sds((R, I), b, rep),
            _sds((R, O), b, rep),
            _sds((O, K), b, rep),
            _sds((O,), b, rep),
            _sds((O, I), b, rep),
            _sds((I, D), i32, rep),
            _sds((I,), f32, rep),
        )
        plans.append(
            ("packer.solve_block_sharded", fn, args, _sig(args), scope)
        )
    return plans


class _X64Lower:
    """Lower-wrapper running the trace under packer.scan_x64(): the fused
    scan's float64/int64 avals only exist in 64-bit mode, and the serve
    path traces under the same scope, so warm-start must too or the
    executable universe would split."""

    def __init__(self, fn):
        self._fn = fn

    def lower(self, *args):
        from karpenter_tpu.ops import packer

        with packer.scan_x64():
            return self._fn.lower(*args)


def _solve_scan_plans(engine, ladder: ladder_mod.Ladder) -> list[tuple]:
    """Fused-scan rungs: one executable per (pods, groups, claims, nodes,
    fams, templates, limited-pools) bucket, through the SAME jitted
    callable the serving path dispatches (packer.solve_scan_fn). Only
    built when the fused path is enabled — a fused-off boot never pays
    the while_loop compiles."""
    from karpenter_tpu.ops import fused as fused_mod
    from karpenter_tpu.ops import packer

    from karpenter_tpu.ops import delta as delta_mod

    plans = []
    for bucket in ladder.buckets("packer.solve_scan"):
        if len(bucket) != 7:
            continue
        _P, _G, _C, N, _F, T, L = bucket
        fn = packer.solve_scan_fn(int(T), N > 0, L > 0)
        args = fused_mod.solve_scan_abstract_args(engine, bucket)
        plans.append(
            ("packer.solve_scan", _X64Lower(fn), args, _sig(args))
        )
        if not delta_mod.delta_enabled():
            continue
        # delta-solve twins of the rung: the cold scan that returns the
        # full 23-component residency state, and the warm resume whose
        # resident-state operands are donated. The donation signature is
        # part of the resume executable's persistent identity (cache_key)
        # — aliasing is compiled in, so a donating entry must never load
        # into the non-donating kernels.
        full = packer.solve_scan_full_fn(int(T), N > 0, L > 0)
        plans.append(
            ("packer.solve_scan_full", _X64Lower(full), args, _sig(args))
        )
        state = fused_mod.solve_scan_state_abstract_args(engine, bucket)
        rargs = args + state + (_sds((), np.int32),)
        resume = packer.solve_scan_resume_fn(int(T), N > 0, L > 0)
        donation = "donate={}-{}".format(
            packer.SCAN_RESUME_DONATE[0], packer.SCAN_RESUME_DONATE[-1]
        )
        plans.append(
            (
                "packer.solve_scan_resume",
                _X64Lower(resume),
                rargs,
                _sig(rargs),
                "",
                donation,
            )
        )
    return plans


def _solve_block_plans(engine, ladder: ladder_mod.Ladder) -> list[tuple]:
    """Packer buckets. The catalog-side row axis is the engine's CURRENT
    interned row count (taken after warmup, when the probe rows exist) —
    rows interned later shift the signature and dispatch off-table, which
    the ladder view surfaces."""
    from karpenter_tpu.ops import packer

    I, O, K = engine.num_instances, engine.num_offerings, engine._key_capacity
    R = max(1, engine._computed_rows)
    D = len(engine.resource_dims)
    b, i32, f32 = np.bool_, np.int32, np.float32
    plans = []
    for (G,) in ladder.buckets("packer.solve_block"):
        args = (
            _sds((G, R + K), b),
            _sds((G, D + 1), i32),
            _sds((R, I), b),
            _sds((R, O), b),
            _sds((O, K), b),
            _sds((O,), b),
            _sds((O, I), b),
            _sds((I, D), i32),
            _sds((I,), f32),
        )
        plans.append(("packer.solve_block", packer.solve_block_jit, args, _sig(args)))
    return plans


# -- the warm start -----------------------------------------------------------


def _ensure_executable(
    plan: tuple,
    catalog_hash: str,
    ladder: ladder_mod.Ladder,
    cache: Optional[ExecutableCache],
    registry,
    summary: dict,
) -> None:
    """Load-or-compile one bucket; installs into the runtime table,
    records the bucket into the observatory (phase aot-warm), and notes
    its HLO cost model into the efficiency tables (once per bucket — the
    perf floor asserts zero per-pass cost_analysis calls; failures
    degrade to absent entries, never a failed boot)."""
    from karpenter_tpu.observability import efficiency

    kernel, fn, abstract_args, sig = plan[:4]
    scope = plan[4] if len(plan) > 4 else ""
    donation = plan[5] if len(plan) > 5 else ""
    summary["buckets"] += 1
    loaded = aotrt.lookup(kernel, sig, scope)
    if loaded is not None:
        # another engine with identical content already warmed this bucket
        # this process — record it like a cache hit so warm-start telemetry
        # is a pure function of the walk, not of process history
        summary["already_loaded"] += 1
        registry.record(kernel, sig, 0.0, compiled=False, fenced=False, aot=True)
        efficiency.note_executable(kernel, sig, loaded, scope=scope)
        return
    from jax.experimental import serialize_executable as se

    key = cache_key(
        catalog_hash, kernel, sig, ladder.version, scope=scope,
        donation=donation,
    )
    t0 = time.perf_counter()
    if cache is not None:
        body = cache.get(key)
        if body is not None:
            try:
                payload, in_tree, out_tree = pickle.loads(body)
                exe = se.deserialize_and_load(payload, in_tree, out_tree)
                aotrt.install(kernel, sig, exe, scope=scope)
                cache.count_hit()  # a hit = an executable actually served
                summary["cache_hits"] += 1
                registry.record(
                    kernel, sig, time.perf_counter() - t0,
                    compiled=False, fenced=False, aot=True,
                )
                # cost tables ride the warm start: one cost_analysis per
                # bucket, answered from the sidecar JSON when the cache
                # already holds it (deserialized executables cost the same)
                efficiency.note_executable(
                    kernel, sig, exe, scope=scope, cache=cache, key=key
                )
                return
            except Exception as e:  # noqa: BLE001 — bad entry: evict, recompile
                cache.evict(key, f"deserialize: {e}")
    try:
        exe = fn.lower(*abstract_args).compile()
    except Exception as e:  # noqa: BLE001 — never crash a boot
        summary["errors"] += 1
        _log.warning(
            "AOT compile failed; kernel stays on lazy JIT",
            kernel=kernel, shape=sig, error=str(e),
        )
        return
    seconds = time.perf_counter() - t0
    aotrt.install(kernel, sig, exe, scope=scope)
    summary["fresh_compiles"] += 1
    registry.record(kernel, sig, seconds, compiled=True, fenced=True, aot=False)
    efficiency.note_executable(
        kernel, sig, exe, scope=scope, cache=cache, key=key
    )
    if cache is not None:
        try:
            body = pickle.dumps(se.serialize(exe))
        except Exception as e:  # noqa: BLE001 — unserializable backend
            summary["errors"] += 1
            _log.warning(
                "AOT executable not serializable; next boot re-compiles",
                kernel=kernel, shape=sig, error=str(e),
            )
            return
        cache.put(key, body)


def warm_start(
    engine,
    ladder: Optional[ladder_mod.Ladder] = None,
    cache: Optional[ExecutableCache] = None,
) -> Optional[dict]:
    """Walk the ladder for `engine`: attach the ladder, stabilize vocab
    capacities, load/compile every bucket, then run the engine's own warmup
    (whose probe dispatch now rides the AOT table). Idempotent per engine.

    A mesh-sharded engine walks the `_sharded` twin plans instead — same
    buckets as GLOBAL shapes, entity axes sharded over its mesh, catalog
    replicated — with the mesh shape folded into both the runtime table
    scope and the persistent cache key, so warm start precompiles the
    sharded executables and the zero-recompile seal holds with the mesh on
    (a restart under a different mesh shape is a cache miss, never a wrong
    load). The row kernel stays single-device on either path (the catalog
    is replicated; rows encode once).

    Returns the walk summary (buckets / cache_hits / fresh_compiles /
    already_loaded / errors), or None when AOT is disabled."""
    if ladder is None:
        ladder = aotrt.active_ladder()
    if cache is None:
        cache = aotrt.active_cache()
    if ladder is None or engine is None:
        if engine is not None:
            engine.warmup()
        return None
    if getattr(engine, "_aot_warmed", False):
        engine.warmup()
        return getattr(engine, "_aot_summary", None)
    from karpenter_tpu.apis import labels as wk
    from karpenter_tpu.ops import catalog as catmod

    summary = {
        "buckets": 0,
        "cache_hits": 0,
        "fresh_compiles": 0,
        "already_loaded": 0,
        "errors": 0,
    }
    engine.aot_ladder = ladder
    # stabilize the key axis: pods constrain with well-known label keys (+
    # hostname), so interning them now means the padded key capacity at
    # boot equals the steady-state one — without this, the first batch's
    # key interning grows K past the AOT'd shapes and every bucket misses
    for key in sorted(set(wk.WELL_KNOWN_LABELS) | {wk.LABEL_HOSTNAME}):
        engine.vocab.key_id(key)
    engine._maybe_reencode()
    catmod.device_rtt_s()  # backend init + routing probe (the seconds part)
    chash = content_hash(engine.instance_types)
    registry = kobs.registry()
    with registry.phase_scope("aot-warm"):
        # a mesh engine serves its sweeps through the sharded twins — the
        # unsharded executables would be dead weight (and vice versa)
        cube_plans = (
            _sharded_cube_plans(engine, ladder)
            if engine.mesh is not None and engine.num_offerings
            else _cube_plans(engine, ladder)
        )
        for plan in cube_plans:
            _ensure_executable(plan, chash, ladder, cache, registry, summary)
        for plan in _row_compat_plans(engine, ladder):
            _ensure_executable(plan, chash, ladder, cache, registry, summary)
        # warmup AFTER the feasibility buckets exist (its probe dispatch
        # rides the table) and BEFORE the packer plans (whose row axis is
        # the post-probe interned row count)
        engine.warmup()
        packer_plans = (
            _sharded_solve_block_plans(engine, ladder)
            if engine.mesh is not None
            else _solve_block_plans(engine, ladder)
        )
        for plan in packer_plans:
            _ensure_executable(plan, chash, ladder, cache, registry, summary)
        # fused-scan rungs: compiled only when the fused path can actually
        # dispatch them (mode on / non-CPU auto) — a fused-off boot pays
        # nothing. Mesh engines compile the scan lazily at first dispatch
        # (pre-seal): the replicated twin is mesh-shape-scoped and cheap.
        from karpenter_tpu.ops import fused as fused_mod

        if fused_mod.fused_enabled() and engine.mesh is None:
            for plan in _solve_scan_plans(engine, ladder):
                _ensure_executable(plan, chash, ladder, cache, registry, summary)
    aotrt.note_warm_start(summary["fresh_compiles"])
    engine._aot_warmed = True
    engine._aot_summary = summary
    _log.info(
        "AOT warm start complete",
        buckets=summary["buckets"],
        cache_hits=summary["cache_hits"],
        fresh_compiles=summary["fresh_compiles"],
        already_loaded=summary["already_loaded"],
        errors=summary["errors"],
    )
    return summary
