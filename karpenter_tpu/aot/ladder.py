"""The shape-bucket ladder: a fixed, versioned set of padded shape buckets
per kernel.

jit executables are keyed by their input shapes, so the set of shapes a
kernel is dispatched with IS the set of executables the process must
compile. The observatory (observability/kernels.py) measures that set per
kernel; the ladder pins it: every device dispatch of a laddered kernel pads
its variable axes up to the smallest bucket that fits, so the universe of
executables is finite, known at boot, and AOT-compilable
(aot/compiler.warm_start). A dispatch that exceeds the largest bucket is an
*off-ladder* dispatch — it still runs (padded to the plain power-of-two
bucket, exactly the pre-ladder behavior) but fires a warning event and a
counter (aot/runtime.note_off_ladder), because it will jit-compile a shape
the AOT walk never prepaid.

Bucket dims are the per-kernel VARIABLE axes only — catalog-determined dims
(instance count, offering count, key/word capacity) come from the engine at
compile time and are part of the cache key, not the ladder:

    feasibility.cube / feasibility.membership : (P, R)  entity x row buckets
    catalog.row_compat                        : (R,)    row-batch bucket
    packer.solve_block                        : (G,)    group bucket

The ladder is versioned (`version` participates in the executable cache
key) and serializable, so a tuned ladder — derived from a production run's
shape-bucket telemetry via `from_observatory` — ships as a JSON artifact
(`--aot-ladder /path/to/ladder.json`).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional, Sequence

LADDER_VERSION = 1

# Kernels the ladder governs, with the number of variable axes each buckets.
# The `_sharded` twins run the same math shard_mapped over a device mesh;
# their buckets are GLOBAL (pre-split) shapes, constrained at lookup time to
# be divisible by the mesh size (bucket_for(multiple_of=)).
LADDER_KERNELS = {
    "feasibility.cube": 2,
    "feasibility.membership": 2,
    "catalog.row_compat": 1,
    "packer.solve_block": 1,
    "feasibility.cube_sharded": 2,
    "packer.solve_block_sharded": 1,
    # the fused FFD scan: (pods, groups, claims, nodes, fams, templates,
    # limited-pools). Its first dispatch arg is the pod axis alone, so the
    # generic first-shape heuristic can't see the other six axes —
    # from_observatory parses its full 27-segment signature instead
    # (_scan_signature_dims), so observed scan telemetry derives trimmed
    # rungs like every other laddered kernel.
    "packer.solve_scan": 7,
}

# Sharded dispatches align their entity axis to a multiple of lcm(mesh size,
# MESH_ALIGN) so the padded GLOBAL shape — the executable key, the
# observatory bucket, the AOT cache identity — is the same for every mesh
# size dividing MESH_ALIGN. That is what lets the mesh-smoke CI job demand
# byte-identical kernel digests at mesh sizes 1 and 8: the mesh changes how
# a shape splits across chips, never which shape dispatches.
MESH_ALIGN = 8


def mesh_multiple(n: int) -> int:
    """The entity-axis alignment for an n-device mesh: lcm(n, MESH_ALIGN)."""
    import math

    return (n * MESH_ALIGN) // math.gcd(max(1, n), MESH_ALIGN)


def _pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


@dataclass(frozen=True)
class Ladder:
    """An immutable bucket ladder: kernel name -> sorted bucket tuples."""

    version: int = LADDER_VERSION
    kernels: dict = field(default_factory=dict)  # name -> tuple[tuple[int,...]]

    def bucket_for(
        self, kernel: str, dims: Sequence[int], multiple_of: int = 1
    ) -> Optional[tuple]:
        """The smallest bucket (by cell count) that fits `dims` on every
        axis, or None when the request is off-ladder (no bucket fits, or the
        kernel has no ladder). `multiple_of` constrains the FIRST axis (the
        sharded entity axis) to buckets divisible by it, so a mesh dispatch
        can split the bucket evenly across its devices."""
        buckets = self.kernels.get(kernel)
        if not buckets:
            return None
        best = None
        best_cells = None
        for b in buckets:
            if len(b) != len(dims):
                continue
            if multiple_of > 1 and b[0] % multiple_of:
                continue
            if all(bd >= d for bd, d in zip(b, dims)):
                cells = 1
                for bd in b:
                    # zero axes (a variant selector like the fused scan's
                    # node/pool dims) must not zero the product, or every
                    # zero-bearing rung would tie at 0 cells and selection
                    # would silently degrade to authoring order
                    cells *= max(bd, 1)
                if best_cells is None or cells < best_cells:
                    best, best_cells = b, cells
        return best

    def buckets(self, kernel: str) -> tuple:
        return self.kernels.get(kernel, ())

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "kernels": {
                name: [list(b) for b in buckets]
                for name, buckets in sorted(self.kernels.items())
            },
        }

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, indent=2)


def _normalize(kernels: dict) -> dict:
    out = {}
    for name, buckets in kernels.items():
        norm = sorted({tuple(int(d) for d in b) for b in buckets})
        out[name] = tuple(norm)
    return out


def make(kernels: dict, version: int = LADDER_VERSION) -> Ladder:
    return Ladder(version=version, kernels=_normalize(kernels))


# The default ladder, sized from the shape-bucket telemetry the kernel
# observatory collected across the sim scenarios and bench legs (PR 6):
# steady-state cube sweeps run at single-digit (P, R); coalesced joint-mask
# sweeps (solverd priming, bench scale) reach hundreds of row-sets over a
# few dozen distinct rows. Row-batch device dispatches only occur for bulk
# encodes (catalog.DEVICE_MIN_ROW_BATCH = 32 and up).
#
# The 128/256/1024 P rungs are the FRONTIER buckets: a consolidation
# frontier round primes the whole round's joint row-sets from its largest
# prefix in ONE sweep, so the union lands between the single-solve bucket
# (64) and the old top rung — without the intermediate rungs every frontier
# compute either 8x-overpadded to 512 or, past 512, jit-compiled a shape
# the AOT walk never prepaid (a steady-state recompile, which the
# observatory seal treats as a bug).
#
# The `_sharded` rungs are GLOBAL (pre-split) shapes for mesh dispatches.
# Every entity rung is a multiple of MESH_ALIGN (8), so one rung serves
# every mesh size dividing 8 with an even shard split and a mesh-size-
# invariant executable key; the 4096 packer rung is the hyperscale ceiling
# (a 1M-pod batch of diverse shapes collapses to low-thousands of groups).
DEFAULT = make(
    {
        "feasibility.cube": [
            (p, r) for p in (1, 8, 64, 128, 256, 512, 1024) for r in (4, 16, 64)
        ],
        "feasibility.membership": [
            (p, r) for p in (1, 8, 64, 128, 256, 512, 1024) for r in (4, 16, 64)
        ],
        "catalog.row_compat": [(32,), (64,), (128,)],
        "packer.solve_block": [(8,), (64,), (512,)],
        "feasibility.cube_sharded": [
            (p, r) for p in (8, 64, 128, 256, 512, 1024) for r in (4, 16, 64)
        ],
        "packer.solve_block_sharded": [(8,), (64,), (512,), (4096,)],
        # fused one-dispatch scan rungs (pods, groups, claims, nodes, fams,
        # templates, limited-pools): the small rungs cover coalesced
        # serving batches and consolidation probe sims (with and without
        # existing nodes), the large one the bulk cold-batch shape. These
        # are padding targets for every fused dispatch; the AOT walk only
        # compiles them when the fused path is enabled (aot/compiler).
        "packer.solve_scan": [
            (512, 64, 256, 0, 64, 1, 0),
            (512, 64, 256, 64, 64, 1, 0),
            (8192, 256, 1024, 0, 128, 1, 0),
        ],
    }
)


def from_dict(data: dict) -> Ladder:
    version = int(data.get("version", LADDER_VERSION))
    return make(dict(data.get("kernels", {})), version=version)


def load(path: str) -> Ladder:
    with open(path, encoding="utf-8") as f:
        return from_dict(json.load(f))


def resolve(spec: str) -> Optional[Ladder]:
    """CLI/option resolution: "" or "off" disables, "default" is the
    built-in ladder, anything else is a JSON ladder file path."""
    if not spec or spec == "off":
        return None
    if spec == "default":
        return DEFAULT
    return load(spec)


def _scan_signature_dims(shape: str):
    """Parse a fused-scan shape signature (27 comma-joined operand
    segments, observability/kernels.shape_signature format) back into its
    7 ladder axes (P, G, C, N, F, T, L), each rounded up to a power of
    two. The variant selectors encode "absent" as 1x1 dummy operands
    (fused.solve_scan_abstract_args), which map back to axis 0 — a rung
    derived from a no-nodes dispatch stays a no-nodes rung."""
    segs = shape.split(",")
    if len(segs) < 27:
        return None
    try:
        P = int(segs[0].split("x")[0])
        C = int(segs[1].split("x")[0])
        G = int(segs[2].split("x")[0])
        T = int(segs[5].split("x")[0])
        F = int(segs[10].split("x")[0])
        n = [int(d) for d in segs[15].split("x")]
        pool = [int(d) for d in segs[24].split("x")]
    except ValueError:
        return None
    N = 0 if n == [1, 1] else n[0]
    L = 0 if pool == [1, 1] else pool[0]
    return tuple(_pow2(d) if d else 0 for d in (P, G, C, N, F, T, L))


def from_observatory(counts_snapshot: dict, headroom: int = 1) -> Ladder:
    """Derive a ladder from observed shape-bucket telemetry — the
    drill-down loop /debug/kernels?view=ladder exists to feed. Each
    observed device bucket of a laddered kernel contributes its variable
    axes rounded up to powers of two; `headroom` extra doublings of the
    largest bucket absorb growth between tuning runs."""
    kernels: dict[str, set] = {name: set() for name in LADDER_KERNELS}
    for name, rec in counts_snapshot.items():
        arity = LADDER_KERNELS.get(name)
        if arity is None:
            continue
        for shape, phases in rec.get("shapes", {}).items():
            # host-twin buckets (their own signature format) never select
            # executables; only device dispatches shape the ladder
            if not (phases.get("warmup") or phases.get("steady")
                    or phases.get("aot-warm")):
                continue
            if name == "packer.solve_scan":
                dims = _scan_signature_dims(shape)
                if dims is not None:
                    kernels[name].add(dims)
                continue
            first = shape.split(",", 1)[0]
            try:
                dims = tuple(_pow2(d) for d in first.split("x"))
            except ValueError:
                continue
            if len(dims) < arity:
                continue
            kernels[name].add(dims[:arity])
    for name, buckets in kernels.items():
        if not buckets:
            continue
        # headroom doubles the PER-AXIS maxima (not the lexicographic top
        # bucket): growth on any observed axis stays on-ladder
        top = tuple(
            max(b[axis] for b in buckets)
            for axis in range(len(next(iter(buckets))))
        )
        for i in range(1, headroom + 1):
            kernels[name].add(tuple(d * (2**i) for d in top))
    return make({k: v for k, v in kernels.items() if v})
