"""Persistent on-disk executable cache: content-keyed, corruption-safe,
concurrent-writer-safe.

Entries are opaque byte blobs (the compiler stores pickled serialized XLA
executables) under sha256 keys; the key embeds everything that makes an
executable valid (catalog content hash, jax/XLA version, device kind,
kernel, bucket signature, ladder version — see aot/compiler.cache_key), so
a mismatch is a MISS, never a wrong load.

Failure discipline — the cache must never be the thing that crashes a
daemon boot:

- corrupted/truncated entry: detected by magic + whole-body sha256
  checksum; the entry is evicted (best-effort unlink), a warning logged,
  and the caller falls back to a fresh JIT compile
- concurrent writers (two daemons sharing a cache dir): writes go to a
  per-writer temp file then `os.replace` — readers only ever see complete
  entries; losing a write race is harmless (both wrote identical bytes)
- read-only/unwritable cache dir: writes degrade to a warning + counter;
  reads (and the daemon) keep working
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Optional

from karpenter_tpu.metrics import global_registry
from karpenter_tpu.operator import logging as klog

_log = klog.logger("aot.cache")

MAGIC = b"KTAOT1\n"
_SUFFIX = ".aotx"

# process-cumulative totals across every cache instance: runtime.stats()
# reads these so deltas stay monotonic even when a re-configure swaps the
# active cache object (per-instance counters live on each cache for
# /debug introspection)
_TOTALS = {"hits": 0, "misses": 0, "evictions": 0, "write_errors": 0}
_totals_lock = threading.Lock()


def totals() -> dict:
    with _totals_lock:
        return dict(_TOTALS)

_HITS = global_registry.counter(
    "karpenter_aot_cache_hits_total",
    "AOT executable cache entries loaded from disk",
)
_MISSES = global_registry.counter(
    "karpenter_aot_cache_misses_total",
    "AOT executable cache lookups that found no entry",
)
_EVICTIONS = global_registry.counter(
    "karpenter_aot_cache_evictions_total",
    "corrupt/unreadable AOT cache entries evicted",
)


class ExecutableCache:
    """One cache directory of checksummed entry files."""

    def __init__(self, root: str):
        self.root = root
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.write_errors = 0
        try:
            os.makedirs(root, exist_ok=True)
        except OSError as e:
            # an uncreatable dir behaves like an empty read-only cache
            _log.warning(
                "AOT cache dir not creatable; cache degraded to misses",
                root=root, error=str(e),
            )

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}{_SUFFIX}")

    # -- reads ---------------------------------------------------------------

    def get(self, key: str) -> Optional[bytes]:
        """The entry's body bytes, or None (miss / evicted-corrupt).

        Does NOT count a hit: "hit" means an executable actually SERVED
        from the cache, which the caller only knows after deserialization
        succeeds — it confirms with ``count_hit()`` (or converts the read
        into an eviction with ``evict()``), so the hits counter the README
        runbook diagnoses from never overstates warm starts."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            self._count("misses")
            _MISSES.inc()
            return None
        except OSError as e:
            _log.warning("AOT cache read failed", key=key, error=str(e))
            self._count("misses")
            _MISSES.inc()
            return None
        body = self._verify(raw)
        if body is None:
            self._evict(key, path, "corrupt or truncated entry")
            return None
        return body

    def count_hit(self) -> None:
        """Confirm a get() whose payload deserialized and loaded."""
        self._count("hits")
        _HITS.inc()

    def evict(self, key: str, reason: str) -> None:
        """Drop an entry whose bytes read clean but whose payload failed to
        load (deserialize error, toolchain drift inside a valid envelope)."""
        self._evict(key, self._path(key), reason)

    @staticmethod
    def _verify(raw: bytes) -> Optional[bytes]:
        if not raw.startswith(MAGIC):
            return None
        head = len(MAGIC)
        digest, body = raw[head : head + 64], raw[head + 65 :]
        if raw[head + 64 : head + 65] != b"\n":
            return None
        if hashlib.sha256(body).hexdigest().encode("ascii") != digest:
            return None
        return body

    def _evict(self, key: str, path: str, reason: str) -> None:
        self._count("evictions")
        _EVICTIONS.inc()
        _log.warning(
            "evicting bad AOT cache entry; falling back to JIT",
            key=key, reason=reason,
        )
        try:
            os.unlink(path)
        except OSError:
            pass  # another writer may have already replaced/removed it

    # -- writes --------------------------------------------------------------

    def put(self, key: str, body: bytes) -> bool:
        """Atomically write an entry; False (plus a warning + counter) when
        the directory is unwritable — the caller's executable still works,
        only the NEXT boot loses the warm start."""
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        blob = (
            MAGIC
            + hashlib.sha256(body).hexdigest().encode("ascii")
            + b"\n"
            + body
        )
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
            return True
        except OSError as e:
            self._count("write_errors")
            _log.warning(
                "AOT cache write failed; next boot will re-compile",
                key=key, error=str(e),
            )
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False

    # -- stats ---------------------------------------------------------------

    def _count(self, name: str) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + 1)
        with _totals_lock:
            _TOTALS[name] += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "root": self.root,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "write_errors": self.write_errors,
            }
