"""State informers: pipe store watch events into Cluster.

The reference runs five thin controllers (pkg/controllers/state/informer/
{node,pod,nodeclaim,nodepool,daemonset}.go) fed by the controller-runtime
cache. Here a single informer drains one watch subscription and dispatches
per kind — same ingestion semantics, one linearized stream.
"""

from __future__ import annotations

import copy

from karpenter_tpu.runtime.store import ADDED, DELETED, MODIFIED, Event, Store
from karpenter_tpu.state.cluster import Cluster

WATCHED_KINDS = ("Node", "Pod", "NodeClaim", "NodePool", "DaemonSet")


class StateInformer:
    def __init__(self, store: Store, cluster: Cluster):
        self.store = store
        self.cluster = cluster
        self._watch = store.watch(WATCHED_KINDS)

    def flush(self) -> int:
        """Apply all pending watch events to cluster state; returns count."""
        events = self._watch.drain()
        for event in events:
            self._apply(event)
        return len(events)

    def bootstrap(self) -> int:
        """Replay every object already in the store into cluster state.

        The watch subscription carries events from construction onward
        only — an operator booted onto a POPULATED store (crash restart,
        adoption of an existing cluster) would otherwise plan against an
        empty Cluster: the scheduler re-provisions capacity that already
        exists and consolidation sees nothing to fold. Kind order matters:
        nodes land before the pods bound to them. Idempotent (cluster
        updates are upserts), so replaying on a warm informer is harmless;
        returns the number of objects replayed."""
        count = 0
        for kind in WATCHED_KINDS:
            for obj in self.store.list(kind):
                self._apply(Event(ADDED, kind, obj))
                count += 1
        return count

    def _apply(self, event: Event) -> None:
        obj = event.obj
        kind = event.kind
        if kind == "Node":
            if event.type == DELETED:
                self.cluster.delete_node(obj.metadata.name)
            else:
                # Snapshot: the store shares objects by reference and
                # controllers mutate them in place, but Cluster diffing
                # (nodepool resource accounting, consolidation triggers,
                # cluster.go:600-646/857-874) needs the PREVIOUS state to
                # stay distinct — real informers deliver fresh object
                # versions per event. Pods skip this (their diffing keys off
                # the bindings map, and they dominate event volume).
                self.cluster.update_node(copy.deepcopy(obj))
        elif kind == "Pod":
            if event.type == DELETED:
                self.cluster.delete_pod(obj.metadata.namespace, obj.metadata.name)
            else:
                self.cluster.update_pod(obj)
        elif kind == "NodeClaim":
            if event.type == DELETED:
                self.cluster.delete_node_claim(obj.metadata.name)
            else:
                self.cluster.update_node_claim(copy.deepcopy(obj))
        elif kind == "NodePool":
            # NodePool changes invalidate consolidation decisions
            # (informer/nodepool.go:45-55).
            self.cluster.mark_unconsolidated()
        elif kind == "DaemonSet":
            if event.type == DELETED:
                self.cluster.delete_daemonset(obj.metadata.namespace, obj.metadata.name)
            else:
                self.cluster.update_daemonset(obj)
