from karpenter_tpu.state.cluster import Cluster  # noqa: F401
from karpenter_tpu.state.statenode import PodBlockEvictionError, StateNode  # noqa: F401
