"""Cluster: the in-memory mirror every solver reads.

Mirrors the reference's pkg/controllers/state/cluster.go:52-874 —
providerID→StateNode, pod bindings, per-nodepool resource accounting, the
Synced() barrier, pod scheduling-decision timestamps, and the consolidation
timestamp. Single-writer by design: the controller loop is single-threaded
(SURVEY.md §2 "TPU-native equivalent" — parallelism lives on-device, not in
host threads), so the reference's RWMutex discipline reduces to plain state.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, Optional

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import DaemonSet, Node, Pod
from karpenter_tpu.apis.nodeclaim import NodeClaim
from karpenter_tpu.apis.nodepool import CONDITION_NODE_REGISTRATION_HEALTHY
from karpenter_tpu.metrics import global_registry
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.state.statenode import StateNode
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import Clock
from karpenter_tpu.utils.resources import ResourceList

if TYPE_CHECKING:
    from karpenter_tpu.cloudprovider.types import CloudProvider

# Pseudo-resource counting nodes in nodepool resource accounting
# (pkg/utils/resources resources.Node).
NODE_RESOURCE = "nodes"

# Consolidation timestamp staleness bound (cluster.go:531-543).
CONSOLIDATION_STATE_TTL = 300.0

_SYNCED_GAUGE = global_registry.gauge(
    "karpenter_cluster_state_synced", "cluster state is synced with the store"
)
_NODE_COUNT_GAUGE = global_registry.gauge(
    "karpenter_cluster_state_node_count", "nodes tracked in cluster state"
)
_UNSYNCED_TIME_GAUGE = global_registry.gauge(
    "karpenter_cluster_state_unsynced_time_seconds",
    "time cluster state has been continuously unsynced (0 when synced)",
)
_DECISION_HIST = global_registry.histogram(
    "karpenter_pods_scheduling_decision_duration_seconds",
    "time from pod ack to first scheduling decision",
)


class Cluster:
    def __init__(self, clock: Clock, store: Store, cloud_provider: "CloudProvider",
                 nomination_window: float = 20.0):
        self.clock = clock
        self.store = store
        self.cloud_provider = cloud_provider
        self.nomination_window = max(10.0, nomination_window)

        self.nodes: dict[str, StateNode] = {}  # provider id -> state node
        self.bindings: dict[tuple[str, str], str] = {}  # pod key -> node name
        self.node_name_to_provider_id: dict[str, str] = {}
        self.node_claim_name_to_provider_id: dict[str, str] = {}
        self.nodepool_resources: dict[str, ResourceList] = {}
        self.daemonset_pods: dict[tuple[str, str], Pod] = {}
        self.anti_affinity_pods: dict[tuple[str, str], Pod] = {}

        self.pod_acks: dict[tuple[str, str], float] = {}
        self.pods_scheduling_attempted: dict[tuple[str, str], float] = {}
        self.pods_schedulable_times: dict[tuple[str, str], float] = {}
        self.pod_healthy_nodepool_scheduled_time: dict[tuple[str, str], float] = {}
        self.pod_to_node_claim: dict[tuple[str, str], str] = {}

        self._consolidation_state = 0.0
        self._has_synced = False
        self._unsynced_since: Optional[float] = None

    # -- sync barrier (cluster.go:113-207) ----------------------------------

    def synced(self) -> bool:
        """True once state covers every NodeClaim and Node in the store and
        every claim has resolved a provider id. Solvers must not run before
        this — they'd double-provision against a partial mirror."""
        if self._has_synced:
            ok = all(pid != "" for pid in self.node_claim_name_to_provider_id.values())
            return self._record_synced(ok)
        claims = {nc.metadata.name for nc in self.store.list("NodeClaim")}
        node_names = {n.metadata.name for n in self.store.list("Node")}
        if any(pid == "" for pid in self.node_claim_name_to_provider_id.values()):
            return self._record_synced(False)
        state_claims = set(self.node_claim_name_to_provider_id)
        state_nodes = set(self.node_name_to_provider_id)
        ok = state_claims >= claims and state_nodes >= node_names
        if ok:
            self._has_synced = True
        return self._record_synced(ok)

    def _record_synced(self, ok: bool) -> bool:
        """Synced gauge + continuously-unsynced stopwatch
        (state/metrics.go:47-62 unsynced_time_seconds)."""
        _SYNCED_GAUGE.set(1.0 if ok else 0.0)
        if ok:
            self._unsynced_since = None
            _UNSYNCED_TIME_GAUGE.set(0.0)
        else:
            if self._unsynced_since is None:
                self._unsynced_since = self.clock.now()
            _UNSYNCED_TIME_GAUGE.set(self.clock.now() - self._unsynced_since)
        return ok

    # -- reads --------------------------------------------------------------

    def state_nodes(self) -> list[StateNode]:
        """Deep copies: callers (solvers) mutate usage on them
        (cluster.go:203-209)."""
        return [n.deep_copy() for n in self.nodes.values()]

    def state_nodes_view(self) -> list[StateNode]:
        """The live StateNode objects, uncopied — for read-only consumers.
        Scheduling solves qualify since ExistingNode went copy-on-write (it
        forks usage onto itself instead of writing through the StateNode),
        which is what lets the consolidation frontier share ONE cluster view
        across k probe simulations instead of deep-copying per probe. The
        caller must not outlive the operator pass it snapshotted in: the
        list is stable only while no informer updates run."""
        return list(self.nodes.values())

    def node_for_pod(self, pod: Pod) -> Optional[StateNode]:
        name = self.bindings.get((pod.metadata.namespace, pod.metadata.name))
        if name is None:
            return None
        return self.nodes.get(self.node_name_to_provider_id.get(name, ""))

    def for_pods_with_anti_affinity(self, fn: Callable[[Pod, Node], bool]) -> None:
        """Iterate bound pods with required anti-affinity (cluster.go:181-198)."""
        for key, pod in list(self.anti_affinity_pods.items()):
            node_name = self.bindings.get(key)
            if node_name is None:
                continue
            state_node = self.nodes.get(self.node_name_to_provider_id.get(node_name, ""))
            if state_node is None or state_node.node is None:
                continue
            if not fn(pod, state_node.node):
                return

    def is_node_nominated(self, provider_id: str) -> bool:
        n = self.nodes.get(provider_id)
        return n is not None and n.nominated(self.clock.now())

    def nominate_node_for_pod(self, provider_id: str) -> None:
        n = self.nodes.get(provider_id)
        if n is not None:
            n.nominate(self.clock.now(), self.nomination_window)

    def node_claim_exists(self, name: str) -> bool:
        return name in self.node_claim_name_to_provider_id

    def nodepool_resources_for(self, nodepool_name: str) -> ResourceList:
        return dict(self.nodepool_resources.get(nodepool_name, {}))

    # -- deletion marks -----------------------------------------------------

    def mark_for_deletion(self, *provider_ids: str) -> None:
        for pid in provider_ids:
            n = self.nodes.get(pid)
            if n is not None:
                old = n.shallow_copy()
                n.marked_for_deletion = True
                self._update_nodepool_resources(old, n)

    def unmark_for_deletion(self, *provider_ids: str) -> None:
        for pid in provider_ids:
            n = self.nodes.get(pid)
            if n is not None:
                old = n.shallow_copy()
                n.marked_for_deletion = False
                self._update_nodepool_resources(old, n)

    # -- node claim ingestion (cluster.go:260-300, 544-566) -----------------

    def update_node_claim(self, node_claim: NodeClaim) -> None:
        pid = node_claim.status.provider_id
        existing_pid = self.node_claim_name_to_provider_id.get(node_claim.metadata.name)
        if pid:
            old = self.nodes.get(pid)
            if existing_pid is not None and existing_pid != pid:
                self._cleanup_node_claim(node_claim.metadata.name)
            n = old.shallow_copy() if old is not None else StateNode()
            n.node_claim = node_claim
            self.nodes[pid] = n
            self._update_nodepool_resources(old, n)
            self._trigger_consolidation_on_change(old, n)
        self.node_claim_name_to_provider_id[node_claim.metadata.name] = pid
        _NODE_COUNT_GAUGE.set(float(len(self.nodes)))

    def delete_node_claim(self, name: str) -> None:
        self._cleanup_node_claim(name)
        _NODE_COUNT_GAUGE.set(float(len(self.nodes)))

    def _cleanup_node_claim(self, name: str) -> None:
        pid = self.node_claim_name_to_provider_id.get(name)
        if pid:
            state_node = self.nodes.get(pid)
            if state_node is not None:
                if state_node.node is None:
                    self._update_nodepool_resources(state_node, None)
                    del self.nodes[pid]
                else:
                    old = state_node.shallow_copy()
                    state_node.node_claim = None
                    self._update_nodepool_resources(old, state_node)
            self.mark_unconsolidated()
        self.node_claim_name_to_provider_id.pop(name, None)

    # -- node ingestion (cluster.go:280-300, 558-583) -----------------------

    def update_node(self, node: Node) -> None:
        managed = bool(node.metadata.labels.get(wk.NODEPOOL_LABEL_KEY))
        initialized = bool(node.metadata.labels.get(wk.NODE_INITIALIZED_LABEL_KEY))
        if node.spec.provider_id == "":
            if managed:
                return
            node.spec.provider_id = node.metadata.name
        # Wait for instance-type label on managed uninitialized nodes so the
        # scheduler never sees a half-labeled node (cluster.go:287-289).
        if managed and not node.metadata.labels.get(wk.LABEL_INSTANCE_TYPE) and not initialized:
            return
        pid = node.spec.provider_id
        existing_pid = self.node_name_to_provider_id.get(node.metadata.name)
        if existing_pid is not None and existing_pid != pid:
            self._cleanup_node(node.metadata.name)
        old = self.nodes.get(pid)
        n = StateNode()
        n.node = node
        if old is not None:
            n.node_claim = old.node_claim
            n.marked_for_deletion = old.marked_for_deletion
            n.nominated_until = old.nominated_until
        self._populate_resource_requests(n)
        self._populate_volume_limits(n)
        self.nodes[pid] = n
        self.node_name_to_provider_id[node.metadata.name] = pid
        self._update_nodepool_resources(old, n)
        self._trigger_consolidation_on_change(old, n)
        _NODE_COUNT_GAUGE.set(float(len(self.nodes)))

    def delete_node(self, name: str) -> None:
        self._cleanup_node(name)
        _NODE_COUNT_GAUGE.set(float(len(self.nodes)))

    def _cleanup_node(self, name: str) -> None:
        pid = self.node_name_to_provider_id.get(name)
        if pid:
            state_node = self.nodes.get(pid)
            if state_node is not None:
                if state_node.node_claim is None:
                    self._update_nodepool_resources(state_node, None)
                    del self.nodes[pid]
                else:
                    old = state_node.shallow_copy()
                    state_node.node = None
                    self._update_nodepool_resources(old, state_node)
            self.node_name_to_provider_id.pop(name, None)
            self.mark_unconsolidated()

    def _populate_resource_requests(self, n: StateNode) -> None:
        node_name = n.node.metadata.name
        for pod in self.store.pods_on_node(node_name):
            if podutil.is_terminal(pod):
                continue
            n.update_for_pod(self.store, pod)
            self._cleanup_old_bindings(pod)
            self.bindings[(pod.metadata.namespace, pod.metadata.name)] = pod.spec.node_name

    def _populate_volume_limits(self, n: StateNode) -> None:
        csi = self.store.try_get("CSINode", n.node.metadata.name)
        if csi is None:
            return
        for driver in csi.drivers:
            if driver.allocatable_count is not None:
                n.volume_usage.add_limit(driver.name, driver.allocatable_count)

    # -- pod ingestion (cluster.go:309-321, 680-720) ------------------------

    def update_pod(self, pod: Pod) -> None:
        if podutil.is_terminal(pod):
            self._update_node_usage_from_pod_completion(
                (pod.metadata.namespace, pod.metadata.name)
            )
        else:
            self._update_node_usage_from_pod(pod)
        self._update_pod_anti_affinities(pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        key = (namespace, name)
        self.anti_affinity_pods.pop(key, None)
        self._update_node_usage_from_pod_completion(key)
        self.clear_pod_scheduling_mappings(key)
        self.mark_unconsolidated()

    def _update_node_usage_from_pod(self, pod: Pod) -> None:
        if pod.spec.node_name == "":
            return
        n = self.nodes.get(self.node_name_to_provider_id.get(pod.spec.node_name, ""))
        if n is None:
            return
        n.update_for_pod(self.store, pod)
        self._cleanup_old_bindings(pod)
        self.bindings[(pod.metadata.namespace, pod.metadata.name)] = pod.spec.node_name

    def _update_node_usage_from_pod_completion(self, key: tuple[str, str]) -> None:
        node_name = self.bindings.pop(key, None)
        if node_name is None:
            return
        n = self.nodes.get(self.node_name_to_provider_id.get(node_name, ""))
        if n is not None:
            n.cleanup_for_pod(*key)

    def _cleanup_old_bindings(self, pod: Pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        old_node_name = self.bindings.get(key)
        if old_node_name is None or old_node_name == pod.spec.node_name:
            return
        old_node = self.nodes.get(self.node_name_to_provider_id.get(old_node_name, ""))
        if old_node is not None:
            old_node.cleanup_for_pod(*key)
            del self.bindings[key]

    def _update_pod_anti_affinities(self, pod: Pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        if podutil.has_required_pod_anti_affinity(pod):
            self.anti_affinity_pods[key] = pod
        else:
            self.anti_affinity_pods.pop(key, None)

    # -- daemonsets (cluster.go:545-576) ------------------------------------

    def update_daemonset(self, daemonset: DaemonSet) -> None:
        """Cache the newest live pod of each daemonset as the template for
        daemon-overhead estimation on future nodes."""
        newest: Optional[Pod] = None
        for p in self.store.list("Pod", namespace=daemonset.metadata.namespace):
            if not any(
                ref.kind == "DaemonSet" and ref.name == daemonset.metadata.name
                for ref in p.metadata.owner_references
            ):
                continue
            if newest is None or p.metadata.creation_timestamp > newest.metadata.creation_timestamp:
                newest = p
        if newest is not None:
            self.daemonset_pods[
                (daemonset.metadata.namespace, daemonset.metadata.name)
            ] = newest

    def get_daemonset_pod(self, daemonset: DaemonSet) -> Optional[Pod]:
        return self.daemonset_pods.get(
            (daemonset.metadata.namespace, daemonset.metadata.name)
        )

    def delete_daemonset(self, namespace: str, name: str) -> None:
        self.daemonset_pods.pop((namespace, name), None)

    # -- pod scheduling decisions (cluster.go:331-436) ----------------------

    def ack_pods(self, *pods: Pod) -> None:
        now = self.clock.now()
        for pod in pods:
            self.pod_acks.setdefault((pod.metadata.namespace, pod.metadata.name), now)

    def pod_ack_time(self, key: tuple[str, str]) -> float:
        return self.pod_acks.get(key, 0.0)

    def mark_pod_scheduling_decisions(
        self,
        pod_errors: dict,
        nodepool_pods: dict[str, list[Pod]],
        nodeclaim_pods: dict[str, list[Pod]],
    ) -> None:
        """Record which pods got a placement this round and which failed
        (drives pod_scheduling_decision/unbound latency metrics)."""
        now = self.clock.now()
        for pod in pod_errors:
            key = (pod.metadata.namespace, pod.metadata.name)
            self.pods_schedulable_times.pop(key, None)
            self._mark_attempted(key, now)
            self.pod_healthy_nodepool_scheduled_time.pop(key, None)
            self.pod_to_node_claim.pop(key, None)
        for nodepool_name, pods in nodepool_pods.items():
            nodepool = (
                self.store.try_get("NodePool", nodepool_name) if nodepool_name else None
            )
            healthy = nodepool is not None and nodepool.condition_is_true(
                CONDITION_NODE_REGISTRATION_HEALTHY
            )
            for p in pods:
                key = (p.metadata.namespace, p.metadata.name)
                self.pods_schedulable_times.setdefault(key, now)
                self._mark_attempted(key, now)
                if healthy:
                    self.pod_healthy_nodepool_scheduled_time.setdefault(key, now)
                else:
                    self.pod_healthy_nodepool_scheduled_time.pop(key, None)
        for nc_name, pods in nodeclaim_pods.items():
            for p in pods:
                self.pod_to_node_claim[(p.metadata.namespace, p.metadata.name)] = nc_name

    def _mark_attempted(self, key: tuple[str, str], now: float) -> None:
        if key not in self.pods_scheduling_attempted:
            self.pods_scheduling_attempted[key] = now
            ack = self.pod_ack_time(key)
            if ack:
                _DECISION_HIST.observe(now - ack)

    def pod_scheduling_decision_time(self, key: tuple[str, str]) -> float:
        return self.pods_scheduling_attempted.get(key, 0.0)

    def pod_scheduling_success_time(self, key: tuple[str, str]) -> float:
        return self.pods_schedulable_times.get(key, 0.0)

    def pod_node_claim_mapping(self, key: tuple[str, str]) -> str:
        return self.pod_to_node_claim.get(key, "")

    def clear_pod_scheduling_mappings(self, key: tuple[str, str]) -> None:
        self.pod_acks.pop(key, None)
        self.pods_schedulable_times.pop(key, None)
        self.pods_scheduling_attempted.pop(key, None)
        self.pod_healthy_nodepool_scheduled_time.pop(key, None)
        self.pod_to_node_claim.pop(key, None)

    # -- consolidation timestamp (cluster.go:517-543) -----------------------

    def mark_unconsolidated(self) -> float:
        self._consolidation_state = self.clock.now()
        return self._consolidation_state

    def consolidation_state(self) -> float:
        state = self._consolidation_state
        if self.clock.now() - state < CONSOLIDATION_STATE_TTL:
            return state
        return self.mark_unconsolidated()

    def _trigger_consolidation_on_change(
        self, old: Optional[StateNode], new: StateNode
    ) -> None:
        """New nodes or initialization/deletion-mark flips invalidate prior
        consolidation decisions (cluster.go:857-874)."""
        if old is None or (old.node is None and old.node_claim is None):
            self.mark_unconsolidated()
            return
        if old.initialized() != new.initialized():
            self.mark_unconsolidated()
        if old.is_marked_for_deletion() != new.is_marked_for_deletion():
            self.mark_unconsolidated()

    # -- nodepool resource accounting (cluster.go:600-646) ------------------

    def _update_nodepool_resources(
        self, old: Optional[StateNode], new: Optional[StateNode]
    ) -> None:
        old_name, old_resources = "", {}
        new_name, new_resources = "", {}
        if old is not None and (old.node is not None or old.node_claim is not None):
            old_name = old.labels().get(wk.NODEPOOL_LABEL_KEY, "")
            old_resources = {} if old.is_marked_for_deletion() else old.capacity()
        if new is not None and (new.node is not None or new.node_claim is not None):
            new_name = new.labels().get(wk.NODEPOOL_LABEL_KEY, "")
            new_resources = {} if new.is_marked_for_deletion() else new.capacity()
        if old_resources:
            old_resources = dict(old_resources)
            old_resources[NODE_RESOURCE] = 1.0
        if new_resources:
            new_resources = dict(new_resources)
            new_resources[NODE_RESOURCE] = 1.0
        if old_name:
            self.nodepool_resources[old_name] = res.subtract_into(
                self.nodepool_resources.get(old_name, {}), old_resources
            )
        if new_name:
            self.nodepool_resources[new_name] = res.merge(
                self.nodepool_resources.get(new_name, {}), new_resources
            )
        for name in (old_name, new_name):
            if name and res.is_zero(self.nodepool_resources.get(name, {})):
                self.nodepool_resources.pop(name, None)

    def reset(self) -> None:
        self.__init__(self.clock, self.store, self.cloud_provider, self.nomination_window)
