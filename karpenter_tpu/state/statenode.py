"""StateNode: the NodeClaim+Node pair view every solver consumes.

Mirrors the reference's pkg/controllers/state/statenode.go:108-534 —
capacity/allocatable fallback (claim status until the node initializes),
taint filtering for uninitialized managed nodes, disruption validity checks,
and per-pod usage tracking (requests, host ports, CSI volumes).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Node, Pod, Taint
from karpenter_tpu.apis.nodeclaim import (
    CONDITION_INSTANCE_TERMINATING,
    NodeClaim,
)
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.scheduling.hostportusage import HostPortUsage, get_host_ports
from karpenter_tpu.scheduling.taints import KNOWN_EPHEMERAL_TAINTS, Taints
from karpenter_tpu.scheduling.volumeusage import VolumeUsage, get_volumes
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.pdb import Limits
from karpenter_tpu.utils.resources import ResourceList


class PodBlockEvictionError(Exception):
    """A pod on the candidate blocks eviction (statenode.go PodBlockEvictionError)."""


class StateNode:
    def __init__(self):
        self.node: Optional[Node] = None
        self.node_claim: Optional[NodeClaim] = None
        self.daemonset_requests: dict[tuple[str, str], ResourceList] = {}
        self.pod_requests: dict[tuple[str, str], ResourceList] = {}
        self.hostport_usage = HostPortUsage()
        self.volume_usage = VolumeUsage()
        self.marked_for_deletion = False
        self.nominated_until = 0.0
        # bumped on every in-place usage mutation (update_for_pod /
        # cleanup_for_pod): consumers caching node-derived statics — the
        # consolidation frontier's ExistingNode prototypes — key on it to
        # invalidate exactly when this node's usage actually moved
        self.usage_seq = 0

    # -- identity -----------------------------------------------------------

    def name(self) -> str:
        if self.node is None:
            return self.node_claim.metadata.name
        if self.node_claim is None:
            return self.node.metadata.name
        if not self.registered():
            return self.node_claim.metadata.name
        return self.node.metadata.name

    def provider_id(self) -> str:
        if self.node is None:
            return self.node_claim.status.provider_id
        return self.node.spec.provider_id

    def hostname(self) -> str:
        return self.labels().get(wk.LABEL_HOSTNAME) or self.name()

    # -- node/claim field resolution (statenode.go:237-349) -----------------

    def labels(self) -> dict[str, str]:
        if self.node is None:
            return self.node_claim.metadata.labels
        if self.node_claim is None or self.registered():
            return self.node.metadata.labels
        return self.node_claim.metadata.labels

    def annotations(self) -> dict[str, str]:
        if self.node is None:
            return self.node_claim.metadata.annotations
        if self.node_claim is None or self.registered():
            return self.node.metadata.annotations
        return self.node_claim.metadata.annotations

    def taints(self) -> Taints:
        """Effective taints; ephemeral + startup taints are invisible on
        uninitialized managed nodes so scheduling can target them
        (statenode.go:299-331)."""
        if (not self.registered() and self.managed()) or self.node is None:
            taints = list(self.node_claim.spec.taints)
        else:
            taints = list(self.node.spec.taints)
        if not self.initialized() and self.managed():
            startup = list(self.node_claim.spec.startup_taints)

            def is_transient(t: Taint) -> bool:
                return any(t.match(e) for e in KNOWN_EPHEMERAL_TAINTS) or any(
                    t.match(s) for s in startup
                )

            taints = [t for t in taints if not is_transient(t)]
        return Taints(taints)

    def managed(self) -> bool:
        return self.node_claim is not None

    def registered(self) -> bool:
        if self.managed():
            return (
                self.node is not None
                and self.node.metadata.labels.get(wk.NODE_REGISTERED_LABEL_KEY) == "true"
            )
        return True

    def initialized(self) -> bool:
        if self.managed():
            return (
                self.node is not None
                and self.node.metadata.labels.get(wk.NODE_INITIALIZED_LABEL_KEY) == "true"
            )
        return True

    def capacity(self) -> ResourceList:
        return self._resolve_resources("capacity")

    def allocatable(self) -> ResourceList:
        return self._resolve_resources("allocatable")

    def _resolve_resources(self, attr: str) -> ResourceList:
        """Until initialization, claim-status values backfill zero/missing
        node values (statenode.go:351-383)."""
        if not self.initialized() and self.node_claim is not None:
            claim_rl = getattr(self.node_claim.status, attr)
            if self.node is not None:
                out = dict(getattr(self.node.status, attr))
                for k, v in claim_rl.items():
                    if abs(out.get(k, 0.0)) < 1e-12:
                        out[k] = v
                return out
            return dict(claim_rl)
        return dict(getattr(self.node.status, attr))

    def available(self) -> ResourceList:
        return res.subtract(self.allocatable(), self.total_pod_requests())

    def total_pod_requests(self) -> ResourceList:
        return res.merge(*self.pod_requests.values())

    def total_daemonset_requests(self) -> ResourceList:
        return res.merge(*self.daemonset_requests.values())

    # -- lifecycle ----------------------------------------------------------

    def deleted(self) -> bool:
        if self.node_claim is not None:
            if self.node_claim.metadata.deletion_timestamp is not None:
                return True
            if self.node_claim.condition_is_true(CONDITION_INSTANCE_TERMINATING):
                return True
        return (
            self.node is not None
            and self.node_claim is None
            and self.node.metadata.deletion_timestamp is not None
        )

    def is_marked_for_deletion(self) -> bool:
        return self.marked_for_deletion or self.deleted()

    def nominate(self, now: float, window: float) -> None:
        self.nominated_until = now + window

    def nominated(self, now: float) -> bool:
        return self.nominated_until > now

    # -- pods ---------------------------------------------------------------

    def pods(self, store: Store) -> list[Pod]:
        if self.node is None:
            return []
        return store.pods_on_node(self.node.metadata.name)

    def reschedulable_pods(self, store: Store) -> list[Pod]:
        return [p for p in self.pods(store) if podutil.is_reschedulable(p)]

    def currently_reschedulable_pods(self, store: Store, pdbs: Limits) -> list[Pod]:
        return [p for p in self.pods(store) if pdbs.is_currently_reschedulable(p)]

    # -- disruption validity (statenode.go:202-262) -------------------------

    def validate_node_disruptable(self, now: float) -> None:
        """Raises ValueError if this node can't be a disruption candidate."""
        if self.node_claim is None:
            raise ValueError("node isn't managed by karpenter")
        if self.node is None:
            raise ValueError("nodeclaim does not have an associated node")
        if not self.initialized():
            raise ValueError("node isn't initialized")
        if self.is_marked_for_deletion():
            raise ValueError("node is deleting or marked for deletion")
        if self.nominated(now):
            raise ValueError("node is nominated for a pending pod")
        if self.annotations().get(wk.DO_NOT_DISRUPT_ANNOTATION_KEY) == "true":
            raise ValueError(
                f'disruption is blocked through the "{wk.DO_NOT_DISRUPT_ANNOTATION_KEY}" annotation'
            )
        if wk.NODEPOOL_LABEL_KEY not in self.labels():
            raise ValueError(f"node doesn't have required label {wk.NODEPOOL_LABEL_KEY}")

    def validate_pods_disruptable(self, store: Store, pdbs: Limits) -> list[Pod]:
        """Raises PodBlockEvictionError if a pod blocks; returns the pods."""
        pods = self.pods(store)
        for p in pods:
            if not podutil.is_disruptable(p):
                raise PodBlockEvictionError(
                    f'pod {p.metadata.namespace}/{p.metadata.name} has '
                    f'"{wk.DO_NOT_DISRUPT_ANNOTATION_KEY}" annotation'
                )
        pdb_keys, ok = pdbs.can_evict_pods(pods)
        if not ok:
            raise PodBlockEvictionError(f"pdb prevents pod evictions: {pdb_keys}")
        return pods

    # -- usage tracking -----------------------------------------------------

    def update_for_pod(self, store: Store, pod: Pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        from karpenter_tpu.apis.core import pod_resource_requests

        self.pod_requests[key] = pod_resource_requests(pod)
        if podutil.is_owned_by_daemon_set(pod):
            self.daemonset_requests[key] = pod_resource_requests(pod)
        self.hostport_usage.add(pod, get_host_ports(pod))
        self.volume_usage.add(pod, get_volumes(store, pod))
        self.usage_seq += 1

    def cleanup_for_pod(self, namespace: str, name: str) -> None:
        self.hostport_usage.delete_pod(namespace, name)
        self.volume_usage.delete_pod(namespace, name)
        self.pod_requests.pop((namespace, name), None)
        self.daemonset_requests.pop((namespace, name), None)
        self.usage_seq += 1

    def deep_copy(self) -> "StateNode":
        """Copy with independent usage tracking, for scheduling simulations
        (reference Cluster.Nodes() deep-copies, cluster.go:203-209): the
        solver mutates hostports/volumes/requests on its copy, never the
        live mirror. The Node/NodeClaim objects stay shared — simulations
        only read them."""
        out = StateNode.__new__(StateNode)
        out.node = self.node
        out.node_claim = self.node_claim
        out.daemonset_requests = {k: dict(v) for k, v in self.daemonset_requests.items()}
        out.pod_requests = {k: dict(v) for k, v in self.pod_requests.items()}
        out.hostport_usage = self.hostport_usage.copy()
        out.volume_usage = self.volume_usage.copy()
        out.marked_for_deletion = self.marked_for_deletion
        out.nominated_until = self.nominated_until
        out.usage_seq = self.usage_seq
        return out

    def shallow_copy(self) -> "StateNode":
        out = StateNode.__new__(StateNode)
        out.node = self.node
        out.node_claim = self.node_claim
        out.daemonset_requests = self.daemonset_requests
        out.pod_requests = self.pod_requests
        out.hostport_usage = self.hostport_usage
        out.volume_usage = self.volume_usage
        out.marked_for_deletion = self.marked_for_deletion
        out.nominated_until = self.nominated_until
        out.usage_seq = self.usage_seq
        return out

    def __repr__(self) -> str:
        return f"StateNode({self.name()!r}, pid={self.provider_id()!r})"


def active(nodes: list[StateNode]) -> list[StateNode]:
    """Nodes eligible as scheduling targets (statenode.go StateNodes.Active)."""
    return [n for n in nodes if not n.is_marked_for_deletion()]


def deleting(nodes: list[StateNode]) -> list[StateNode]:
    return [n for n in nodes if n.is_marked_for_deletion()]


def require_no_schedule_taint(store: Store, add: bool, *nodes: StateNode) -> None:
    """Add/remove the karpenter.sh/disrupted:NoSchedule taint on the Node
    objects (statenode.go:483-534). Idempotent; deleting nodes keep it."""
    from karpenter_tpu.scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT

    for sn in nodes:
        if sn.node is None or sn.node_claim is None:
            continue
        node = store.try_get("Node", sn.node.metadata.name)
        if node is None:
            continue
        has = any(t.match(DISRUPTED_NO_SCHEDULE_TAINT) for t in node.spec.taints)
        if has and node.metadata.deletion_timestamp is not None:
            continue
        if not add and has:
            node.spec.taints = [
                t for t in node.spec.taints if not t.match(DISRUPTED_NO_SCHEDULE_TAINT)
            ]
            store.update(node)
        elif add and not has:
            node.spec.taints = list(node.spec.taints) + [DISRUPTED_NO_SCHEDULE_TAINT]
            store.update(node)


def clear_node_claims_condition(store: Store, condition_type: str, *nodes: StateNode) -> None:
    """Strip a status condition from the nodes' NodeClaims
    (statenode.go ClearNodeClaimsCondition)."""
    for sn in nodes:
        if sn.node_claim is None:
            continue
        claim = store.try_get("NodeClaim", sn.node_claim.metadata.name)
        if claim is None or claim.get_condition(condition_type) is None:
            continue
        claim.clear_condition(condition_type)
        store.update(claim)
