"""Per-pod scheduling-journey assembly.

A pod's journey is the causally-ordered hop sequence
first-seen-pending → batcher flush → solverd admit → solve →
NodeClaim create → cloud launch → registration → bind, reconstructed
STREAMING from finished spans (the recorder is just another exporter) so
it works identically for the live operator's ring-buffered traces and the
simulator's full span log.

Span → stage mapping:

    pod.pending              pending       (first trigger → batch flush)
    solverd.queue            admit         (admission → batch drain), per trace
    solverd.solve            solve         (batch execution), per trace
    nodeclaim.create         create        per claim
    nodeclaim.launch (ok)    launch        per claim (cloud create)
    nodeclaim.registration   registration  per claim (launch → node joined)
    pod.bind                 bind          (previous stage end → bind)

Claim-level stages fan out to every pod scheduled onto that claim; a pod
that bound straight to existing capacity legitimately has a bind-only
journey. Completed journeys feed the per-stage histograms
``karpenter_pod_scheduling_duration_seconds{stage=}`` and the sim report's
per-stage p50/p99.
"""

from __future__ import annotations

import threading
from bisect import insort
from collections import OrderedDict, deque

from karpenter_tpu.metrics import global_registry
from karpenter_tpu.utils.stats import percentile

STAGES = ("pending", "admit", "solve", "create", "launch", "registration", "bind")

_STAGE_HIST = global_registry.histogram(
    "karpenter_pod_scheduling_duration_seconds",
    "per-stage pod scheduling journey duration",
    labels=["stage"],
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0),
)


def _bounded(d: OrderedDict, cap: int) -> None:
    while len(d) > cap:
        d.popitem(last=False)


def _pod_key(attrs: dict) -> str:
    # uid when the span carries one (names collide across namespaces and
    # across a recreated pod's lifetimes; uids never do), name as fallback
    # for hand-rolled spans
    return attrs.get("pod_uid") or attrs.get("pod", "")


class JourneyRecorder:
    """Exporter that folds spans into per-pod journeys."""

    def __init__(self, max_completed: int = 1024, max_in_flight: int = 8192):
        self._lock = threading.Lock()
        # pod name -> {"trace", "claim", "node", "stages": {stage: (s, e)}}
        self._pods: OrderedDict[str, dict] = OrderedDict()
        # claim name -> {stage: (s, e)}
        self._claims: OrderedDict[str, dict] = OrderedDict()
        # trace id -> {"admit": (s, e), "solve": (s, e)}
        self._trace_stages: OrderedDict[str, dict] = OrderedDict()
        self._completed: deque = deque(maxlen=max_completed)
        self._max_in_flight = max_in_flight
        self._durations: dict[str, list[float]] = {s: [] for s in STAGES}
        self._durations["total"] = []
        self.completed_count = 0

    # -- exporter interface --------------------------------------------------

    def export(self, d: dict) -> None:
        name = d.get("name", "")
        attrs = d.get("attrs") or {}
        with self._lock:
            if name == "pod.pending":
                rec = self._pod(_pod_key(attrs))
                rec["pod"] = attrs.get("pod", "")
                rec["trace"] = d.get("trace")
                rec["stages"]["pending"] = (d["start"], d["end"])
            elif name == "pod.schedule":
                rec = self._pod(_pod_key(attrs))
                rec["pod"] = attrs.get("pod", "")
                rec["trace"] = d.get("trace")
                if attrs.get("nodeclaim"):
                    rec["claim"] = attrs["nodeclaim"]
                if attrs.get("node"):
                    rec["node"] = attrs["node"]
            elif name == "solverd.queue":
                self._trace(d.get("trace")).setdefault(
                    "admit", (d["start"], d["end"])
                )
            elif name == "solverd.solve":
                self._trace(d.get("trace")).setdefault(
                    "solve", (d["start"], d["end"])
                )
            elif name == "nodeclaim.create":
                claim = self._claim(attrs.get("nodeclaim", ""))
                claim["create"] = (d["start"], d["end"])
            elif name == "nodeclaim.launch" and d.get("status") == "ok":
                self._claim(attrs.get("nodeclaim", "")).setdefault(
                    "launch", (d["start"], d["end"])
                )
            elif name == "nodeclaim.registration":
                self._claim(attrs.get("nodeclaim", "")).setdefault(
                    "registration", (d["start"], d["end"])
                )
            elif name == "pod.bind":
                self._finalize(attrs, d)

    # -- state ---------------------------------------------------------------

    def _pod(self, key: str) -> dict:
        rec = self._pods.get(key)
        if rec is None:
            rec = self._pods[key] = {
                "pod": "", "trace": None, "claim": None, "node": None,
                "stages": {},
            }
        _bounded(self._pods, self._max_in_flight)
        return rec

    def _claim(self, claim: str) -> dict:
        stages = self._claims.get(claim)
        if stages is None:
            stages = self._claims[claim] = {}
        _bounded(self._claims, self._max_in_flight)
        return stages

    def _trace(self, trace_id: str) -> dict:
        stages = self._trace_stages.get(trace_id)
        if stages is None:
            stages = self._trace_stages[trace_id] = {}
        _bounded(self._trace_stages, self._max_in_flight)
        return stages

    def _finalize(self, attrs: dict, bind_span: dict) -> None:
        pod = attrs.get("pod", "")
        rec = self._pods.pop(_pod_key(attrs), None) or {
            "pod": pod, "trace": None, "claim": None, "node": None,
            "stages": {},
        }
        stages: dict[str, tuple] = dict(rec["stages"])
        trace_id = rec["trace"] or bind_span.get("trace")
        if rec["trace"] in self._trace_stages:
            for stage, window in self._trace_stages[rec["trace"]].items():
                stages.setdefault(stage, window)
        claim = rec["claim"] or attrs.get("nodeclaim") or None
        if claim and claim in self._claims:
            for stage, window in self._claims[claim].items():
                stages.setdefault(stage, window)
        bind_t = bind_span["end"]
        prev_end = max((e for _, e in stages.values()), default=bind_span["start"])
        stages["bind"] = (min(prev_end, bind_t), bind_t)
        first_start = min(s for s, _ in stages.values())
        journey = {
            "pod": pod,
            "trace": trace_id,
            "nodeclaim": claim,
            "node": rec["node"] or attrs.get("node"),
            "bound_at": bind_t,
            "total": round(bind_t - first_start, 6),
            "stages": {
                stage: {
                    "start": round(s, 6),
                    "end": round(e, 6),
                    "duration": round(e - s, 6),
                }
                for stage, (s, e) in sorted(
                    stages.items(), key=lambda kv: (kv[1][0], kv[1][1])
                )
            },
        }
        self._completed.append(journey)
        self.completed_count += 1
        for stage, (s, e) in stages.items():
            self._observe(stage, e - s)
        self._observe("total", journey["total"])
        # SLO feed: the solverd hops of the journey — admission wait plus
        # batch execution — classified against the solve-latency objective.
        # This is exactly the karpenter_pod_scheduling_duration_seconds
        # stage data, re-read as a burn-rate series.
        from karpenter_tpu.observability import slo

        for stage in ("admit", "solve"):
            window = stages.get(stage)
            if window is not None:
                slo.engine().observe(
                    "solve-latency", max(0.0, window[1] - window[0])
                )

    def _observe(self, stage: str, duration: float) -> None:
        _STAGE_HIST.observe(max(0.0, duration), {"stage": stage})
        values = self._durations.setdefault(stage, [])
        if len(values) < 200_000:  # sim-scale bound; stats stay exact below it
            # keep the list sorted as it grows: stats() reads percentiles
            # under the same lock the span hot path exports through, so it
            # must not re-sort the whole history per /debug/traces hit
            insort(values, max(0.0, duration))

    # -- queries -------------------------------------------------------------

    def stats(self) -> dict:
        """Per-stage duration distribution over completed journeys."""
        with self._lock:
            out: dict = {
                "completed": self.completed_count,
                "in_flight": len(self._pods),
                "stages": {},
            }
            for stage, values in self._durations.items():
                if not values:
                    continue
                # values is maintained sorted by _observe
                out["stages"][stage] = {
                    "count": len(values),
                    "p50": percentile(values, 50),
                    "p99": percentile(values, 99),
                    "max": values[-1],
                }
            return out

    def completed(self) -> list[dict]:
        with self._lock:
            return list(self._completed)

    def slowest(self, limit: int = 10) -> list[dict]:
        with self._lock:
            ranked = sorted(
                self._completed, key=lambda j: j["total"], reverse=True
            )
        return ranked[:limit]

    def for_trace(self, trace_id: str) -> list[dict]:
        with self._lock:
            return [j for j in self._completed if j["trace"] == trace_id]
