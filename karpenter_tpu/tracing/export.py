"""Span exporters: ring buffer, rolling digest, and JSONL file.

Every exporter consumes the same canonical span dict (``Span.to_dict``
applied by the tracer), so the digest, the JSONL file, and the debug
surface can never disagree about what a span contained. ``canonical``
mirrors ``sim/events.py``: sorted keys, explicit separators — the byte
layout IS the determinism contract.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict, deque
from typing import Optional


def canonical(span: dict) -> str:
    return json.dumps(span, sort_keys=True, separators=(",", ":"))


class RingBufferExporter:
    """Last-N finished spans, evicted strictly oldest-first. Backs
    ``/debug/traces``: grouping the buffer by trace id reconstructs recent
    traces without unbounded memory."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._spans: deque = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()

    def export(self, span: dict) -> None:
        with self._lock:
            self._spans.append(span)

    def spans(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def trace(self, trace_id: str) -> list[dict]:
        with self._lock:
            out = [d for d in self._spans if d.get("trace") == trace_id]
        out.sort(key=lambda d: (d.get("start", 0.0), d.get("end", 0.0)))
        return out

    def take_trace(self, trace_id: str) -> list[dict]:
        """Remove and return one trace's spans (the solverd daemon ships a
        request's spans back exactly once, in the reply frame)."""
        with self._lock:
            keep, taken = deque(maxlen=self._spans.maxlen), []
            for d in self._spans:
                (taken if d.get("trace") == trace_id else keep).append(d)
            self._spans = keep
        taken.sort(key=lambda d: (d.get("start", 0.0), d.get("end", 0.0)))
        return taken

    def summaries(self, limit: int = 20) -> list[dict]:
        """Most-recent traces (by last finished span), newest first: root
        name, span count, start/end bounds, error count."""
        if limit <= 0:
            return []
        with self._lock:
            snapshot = list(self._spans)
        traces: "OrderedDict[str, dict]" = OrderedDict()
        for d in snapshot:
            tid = d.get("trace")
            entry = traces.get(tid)
            if entry is None:
                entry = traces[tid] = {
                    "trace_id": tid,
                    "root": None,
                    "spans": 0,
                    "errors": 0,
                    "start": d.get("start"),
                    "end": d.get("end"),
                }
            else:
                # re-append so insertion order tracks recency of activity
                traces.move_to_end(tid)
            entry["spans"] += 1
            entry["start"] = min(entry["start"], d.get("start", entry["start"]))
            entry["end"] = max(entry["end"], d.get("end", entry["end"]))
            if d.get("status") == "error":
                entry["errors"] += 1
            if d.get("parent") is None:
                entry["root"] = d.get("name")
        out = list(traces.values())[-limit:]
        out.reverse()
        for entry in out:
            entry["duration"] = round(entry["end"] - entry["start"], 6)
        return out


class DigestExporter:
    """sha256 over the canonical line of every exported span — the span-log
    fingerprint the sim report embeds. O(1) memory; never stores spans."""

    def __init__(self):
        self._hash = hashlib.sha256()
        self.count = 0
        self._lock = threading.Lock()

    def export(self, span: dict) -> None:
        line = canonical(span).encode()
        with self._lock:
            self._hash.update(line)
            self._hash.update(b"\n")
            self.count += 1

    def digest(self) -> str:
        with self._lock:
            return "sha256:" + self._hash.hexdigest()


class JSONLExporter:
    """One canonical JSON line per span, appended as spans finish. Two
    same-seed deterministic runs write byte-identical files."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "w", encoding="utf-8")
        self._lock = threading.Lock()

    def export(self, span: dict) -> None:
        line = canonical(span)
        with self._lock:
            if self._f is not None:
                self._f.write(line + "\n")

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
