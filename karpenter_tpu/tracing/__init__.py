"""tracing: end-to-end scheduling traces over the injected-Clock substrate.

The observability layer PRs 1–3 lacked: a span API whose ids come from the
seeded uid source and whose timestamps come from the injected Clock, so
same-seed simulator runs emit byte-identical span logs (the digest is
asserted in CI next to the event-log digest). Instrumented hops: every
harness-wrapped reconcile, the provisioner's per-batch trace (child spans
per pod), solverd admission/coalescing/solve on both transports (trace
context rides the request envelope; daemon-side spans ship back in the
reply frame), cloud-provider create/delete with breaker state, nodeclaim
launch/registration, and binding. ``journey.JourneyRecorder`` assembles
the per-pod scheduling journey; ``/debug/traces`` serves it.

Controllers reach the tracer through the module-global accessor — the same
pattern as ``metrics.global_registry`` — because threading a tracer through
~25 constructor signatures would be plumbing for its own sake. The operator
(and the simulator, and the solverd daemon) call ``configure()`` once at
startup with their clock and options.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.tracing.core import (  # noqa: F401
    CURRENT,
    Span,
    SpanContext,
    Tracer,
    current,
)
from karpenter_tpu.tracing.export import (  # noqa: F401
    DigestExporter,
    JSONLExporter,
    RingBufferExporter,
    canonical,
)
from karpenter_tpu.tracing.journey import JourneyRecorder  # noqa: F401

_tracer: Optional[Tracer] = None


def configure(
    clock=None,
    sample_rate: float = 1.0,
    buffer_size: int = 4096,
    deterministic: bool = False,
    jsonl_path: Optional[str] = None,
) -> Tracer:
    """Install the process-global tracer (closing any previous one's file
    exporters) and return it. The standard exporter set is always wired:
    ring buffer (``/debug/traces``), rolling digest, journey recorder —
    plus a JSONL file when ``jsonl_path`` is given."""
    global _tracer
    if _tracer is not None:
        _tracer.close()
    tr = Tracer(
        clock=clock,
        sample_rate=sample_rate,
        deterministic=deterministic,
        buffer_size=buffer_size,
    )
    if jsonl_path:
        tr.exporters.append(JSONLExporter(jsonl_path))
    _tracer = tr
    return tr


def tracer() -> Tracer:
    """The process-global tracer (lazily constructed with defaults)."""
    global _tracer
    if _tracer is None:
        configure()
    return _tracer
