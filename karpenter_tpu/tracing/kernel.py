"""Kernel wall-time attribution: compile vs execute, per solve — and the
instrumented-dispatch choke point feeding the kernel observatory.

The solve span wants to answer "was this solve slow because XLA compiled a
new executable, or because the device executed a big cube?" — the split
the ROADMAP's solver tuning needs. JAX exposes no per-dispatch hook, so the
attribution is structural: every device dispatch in the solver goes through
``dispatch()``, which fences with ``block_until_ready`` and classifies the
wall time by the jitted callable's compile-cache delta (a dispatch that
grew the cache paid a compile; one that didn't ran a warm executable).

Measurements accumulate into a contextvar-scoped dict opened by
``measure()`` (the solverd coalescer wraps each request's solve in one), so
nested dispatches attribute to the request that triggered them and
concurrent daemon threads never mix accounts. All numbers here are
wall-clock — span code must record them as VOLATILE attrs, never in the
deterministic digest.

Each dispatch's wall is additionally split into enqueue (the host-side
call: tracing, argument staging, nested dispatches, any compile) vs block
(the ``block_until_ready`` wait — device work the host demonstrably
waited on). The split feeds the efficiency observatory's per-batch
host-stall timeline (observability/efficiency.py); unfenced dispatches
report zero block wall because their device work was never awaited here.

Nesting: a fenced dispatch whose callable itself dispatches (a host driver
wrapping an inner kernel) attributes wall time to the INNERMOST dispatch
only — each frame subtracts its children's elapsed time before recording,
so the measure() totals and the registry's per-kernel walls never double
count one second of device work.

Named dispatches (``kernel="packer.solve_block"``) additionally report to
``observability/kernels.KernelRegistry``: compile counts, the padded input
shape signature, and the warmup/steady phase label — recorded even OUTSIDE
a measurement context (prewarm compiles must be attributed), but fenced
only when a context is open or a compile happened, so tracing-off hot
paths keep their async dispatch pipeline.
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager
from typing import Iterator, Optional

from karpenter_tpu.aot import runtime as aotrt
from karpenter_tpu.observability import kernels as kobs

_ACC: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "karpenter_kernel_acc", default=None
)
# per-thread-of-control dispatch nesting stack: each frame is a one-cell
# list accumulating its CHILDREN's elapsed seconds (see dispatch)
_NEST: contextvars.ContextVar[Optional[list]] = contextvars.ContextVar(
    "karpenter_kernel_nest", default=None
)


def _fresh() -> dict:
    return {
        "compile_s": 0.0,
        "execute_s": 0.0,
        "dispatches": 0,
        "compiles": 0,
        # the execute wall split (efficiency observatory): enqueue_s is the
        # host-side call (tracing, arg staging, dispatch), block_s the
        # block_until_ready wait — device work the host genuinely waited on.
        # Both sum into compile_s/execute_s above; they are the same wall,
        # attributed twice at different grain.
        "enqueue_s": 0.0,
        "block_s": 0.0,
    }


@contextmanager
def measure() -> Iterator[dict]:
    """Collect kernel dispatch timings for everything run inside."""
    acc = _fresh()
    token = _ACC.set(acc)
    try:
        yield acc
    finally:
        _ACC.reset(token)


def _cache_size(fn) -> Optional[int]:
    try:
        return fn._cache_size()  # jax.jit wrappers expose this
    except Exception:  # noqa: BLE001 — attribution is best-effort
        return None


def dispatch(fn, *args, kernel: Optional[str] = None, aot_scope: str = ""):
    """Call a jitted function, block until its outputs are ready, and
    attribute the wall time to compile or execute. Transparent (returns the
    outputs) and free when no measurement context is open and no kernel
    name is given.

    Named dispatches first consult the AOT executable table
    (aot/runtime.py): a (kernel, shape) the warm start prepaid executes the
    loaded executable directly — no jit cache, no compile, so a
    warm-started daemon's first solve pays zero compiles. An AOT
    executable that fails at call time (backend drift) is discarded and
    the dispatch falls back to the jit path. `aot_scope` narrows the table
    lookup to executables compiled for a specific device layout (the mesh
    shape of a shard_mapped kernel); it never reaches the observatory, so
    kernel telemetry stays a pure function of the dispatched shapes."""
    acc = _ACC.get()
    if acc is None and kernel is None:
        return fn(*args)
    sig = kobs.shape_signature(args) if kernel is not None else None
    aexe = aotrt.lookup(kernel, sig, aot_scope)
    stack = _NEST.get()
    if stack is None:
        stack = []
        _NEST.set(stack)
    cell = [0.0]  # children's elapsed accumulates here
    stack.append(cell)
    t0 = time.perf_counter()
    t_enqueued = None  # set once the call returns, before any fence
    compiled = False
    served_aot = False
    fenced = False
    try:
        if aexe is not None:
            try:
                out = aexe(*args)
                served_aot = True
            except Exception as e:  # noqa: BLE001 — degrade to JIT, never fail
                aotrt.discard(
                    kernel, sig,
                    error=f"{type(e).__name__}: {e}", scope=aot_scope,
                )
        if not served_aot:
            before = _cache_size(fn)
            out = fn(*args)
            after = _cache_size(fn)
            compiled = (
                before is not None and after is not None and after > before
            )
        # the dispatch-timeline split (efficiency observatory): everything
        # up to here is ENQUEUE wall (host-side tracing/staging + any
        # compile + the children's nested dispatches); the fence below is
        # BLOCK wall — time the host demonstrably spent waiting on device
        t_enqueued = time.perf_counter()
        # fence when a measurement context wants exact execute wall, or when
        # a compile happened (compile wall must be exact for the registry's
        # recompile accounting; compiles are rare so the fence is free)
        fenced = acc is not None or compiled
        if fenced:
            try:
                import jax

                jax.block_until_ready(out)
            except Exception:  # noqa: BLE001 — host twins return plain numpy
                pass
    finally:
        elapsed = time.perf_counter() - t0
        stack.pop()
    # innermost-only attribution: subtract the children's wall, credit the
    # parent frame with our FULL elapsed so it subtracts us in turn. The
    # children ran inside the CALL, so they subtract from the enqueue
    # segment only; block wall is always this frame's own.
    self_s = max(0.0, elapsed - cell[0])
    block_s = elapsed - (t_enqueued - t0) if t_enqueued is not None else 0.0
    enqueue_s = max(0.0, self_s - block_s)
    if stack:
        stack[-1][0] += elapsed
    if acc is not None:
        acc["dispatches"] += 1
        acc["enqueue_s"] += enqueue_s
        acc["block_s"] += block_s
        if compiled:
            acc["compiles"] += 1
            acc["compile_s"] += self_s
        else:
            acc["execute_s"] += self_s
    if kernel is not None:
        kobs.registry().record(
            kernel, sig, self_s, compiled, fenced, aot=served_aot,
            enqueue_s=enqueue_s, block_s=block_s,
        )
    return out
