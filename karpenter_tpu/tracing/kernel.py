"""Kernel wall-time attribution: compile vs execute, per solve.

The solve span wants to answer "was this solve slow because XLA compiled a
new executable, or because the device executed a big cube?" — the split
the ROADMAP's solver tuning needs. JAX exposes no per-dispatch hook, so the
attribution is structural: every device dispatch in the solver goes through
``dispatch()``, which fences with ``block_until_ready`` and classifies the
wall time by the jitted callable's compile-cache delta (a dispatch that
grew the cache paid a compile; one that didn't ran a warm executable).

Measurements accumulate into a contextvar-scoped dict opened by
``measure()`` (the solverd coalescer wraps each request's solve in one), so
nested dispatches attribute to the request that triggered them and
concurrent daemon threads never mix accounts. All numbers here are
wall-clock — span code must record them as VOLATILE attrs, never in the
deterministic digest.
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager
from typing import Iterator, Optional

_ACC: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "karpenter_kernel_acc", default=None
)


def _fresh() -> dict:
    return {"compile_s": 0.0, "execute_s": 0.0, "dispatches": 0, "compiles": 0}


@contextmanager
def measure() -> Iterator[dict]:
    """Collect kernel dispatch timings for everything run inside."""
    acc = _fresh()
    token = _ACC.set(acc)
    try:
        yield acc
    finally:
        _ACC.reset(token)


def _cache_size(fn) -> Optional[int]:
    try:
        return fn._cache_size()  # jax.jit wrappers expose this
    except Exception:  # noqa: BLE001 — attribution is best-effort
        return None


def dispatch(fn, *args):
    """Call a jitted function, block until its outputs are ready, and
    attribute the wall time to compile or execute. Transparent (returns the
    outputs) and free when no measurement context is open."""
    acc = _ACC.get()
    if acc is None:
        return fn(*args)
    before = _cache_size(fn)
    t0 = time.perf_counter()
    out = fn(*args)
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 — host twins return plain numpy
        pass
    elapsed = time.perf_counter() - t0
    after = _cache_size(fn)
    compiled = before is not None and after is not None and after > before
    acc["dispatches"] += 1
    if compiled:
        acc["compiles"] += 1
        acc["compile_s"] += elapsed
    else:
        acc["execute_s"] += elapsed
    return out
