"""Span primitives: contexts, spans, and the Tracer.

A Dapper-style span layer (Sigelman et al., 2010) over the repo's injected
infrastructure: timestamps come from the injected ``Clock`` and trace/span
ids from the seeded uid source in ``apis/core`` — so a simulation run under
``FakeClock`` + ``set_uid_source`` emits byte-identical spans for identical
seeds. That makes traces *deterministically replayable*: the span-log
digest is a regression fingerprint exactly like the sim's event-log digest.

Two attribute classes keep that contract honest:

- regular attrs (``set_attr``) must be pure functions of the scenario —
  names, counts, outcomes — and are always exported;
- volatile attrs (``set_volatile``) are wall-clock measurements and
  process-history counters (kernel compile/execute split, cache-hit
  deltas) that legitimately differ between replays; a ``deterministic``
  tracer drops them at export so the digest never sees them.

Context propagation is explicit where it must be (a carrier dict rides the
solverd request envelope across BOTH transports) and ambient where it can
be (a contextvar tracks the active span within a thread of control, so
nested instrumentation links up without plumbing).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Iterator, Optional

from karpenter_tpu.utils.clock import Clock

# sentinel: "parent not specified — fall back to the ambient current span".
# Passing parent=None explicitly means "root: start a new trace" (the
# provisioner's per-batch traces), which a plain default could not express.
CURRENT = object()

import contextvars

_ACTIVE: contextvars.ContextVar[Optional["SpanContext"]] = contextvars.ContextVar(
    "karpenter_active_span", default=None
)


@dataclass(frozen=True)
class SpanContext:
    trace_id: str
    span_id: str
    sampled: bool = True


class Span:
    __slots__ = ("name", "context", "parent_id", "start", "end", "status",
                 "attrs", "vattrs")

    def __init__(
        self,
        name: str,
        context: SpanContext,
        parent_id: Optional[str],
        start: float,
        **attrs: Any,
    ):
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.start = start
        self.end: Optional[float] = None
        self.status = "ok"
        self.attrs: dict[str, Any] = dict(attrs)
        self.vattrs: dict[str, Any] = {}

    @property
    def sampled(self) -> bool:
        return self.context.sampled

    def set_attr(self, **kv: Any) -> None:
        self.attrs.update(kv)

    def set_volatile(self, **kv: Any) -> None:
        """Wall-clock / process-history attributes: excluded from
        deterministic export (they differ between same-seed replays)."""
        self.vattrs.update(kv)

    def fail(self, err: BaseException) -> None:
        self.status = "error"
        self.attrs["error"] = f"{type(err).__name__}: {err}"

    def to_dict(self, deterministic: bool = False) -> dict:
        attrs = dict(self.attrs)
        if not deterministic:
            attrs.update(self.vattrs)
        d: dict[str, Any] = {
            "trace": self.context.trace_id,
            "span": self.context.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "start": round(self.start, 6),
            "end": round(self.end if self.end is not None else self.start, 6),
            "status": self.status,
            "attrs": attrs,
        }
        return d


class _NullSpan:
    """Stand-in for an unsampled span: carries an unsampled context so
    children skip too; every mutator is a no-op."""

    __slots__ = ("context",)

    def __init__(self, context: SpanContext):
        self.context = context

    sampled = False

    def set_attr(self, **kv: Any) -> None:
        pass

    def set_volatile(self, **kv: Any) -> None:
        pass

    def fail(self, err: BaseException) -> None:
        pass


def current() -> Optional[SpanContext]:
    """The ambient active span context (None outside any span)."""
    return _ACTIVE.get()


class Tracer:
    """Creates, contextualizes, and exports spans.

    ``exporters`` consume finished spans as plain dicts (``Span.to_dict``
    with the tracer's determinism applied), so every exporter — ring
    buffer, digest, JSONL file, journey assembler — sees one canonical
    shape. The tracer also keeps the *journey link table*: a bounded map
    from (kind, name) — e.g. ``("pod", "train-3")`` or ``("nodeclaim",
    "workers-ab12cd34")`` — to the span context later hops (lifecycle
    launch/registration, binding) re-join, which is what stitches a pod's
    multi-pass journey into ONE trace.
    """

    def __init__(
        self,
        clock: Optional[Clock] = None,
        sample_rate: float = 1.0,
        deterministic: bool = False,
        buffer_size: int = 4096,
        link_capacity: int = 8192,
    ):
        from karpenter_tpu.tracing.export import DigestExporter, RingBufferExporter
        from karpenter_tpu.tracing.journey import JourneyRecorder

        self.clock = clock or Clock()
        self.sample_rate = sample_rate
        self.deterministic = deterministic
        self.ring = RingBufferExporter(buffer_size)
        self.digest = DigestExporter()
        self.journeys = JourneyRecorder()
        self.exporters: list = [self.ring, self.digest, self.journeys]
        self._links: OrderedDict[tuple[str, str], SpanContext] = OrderedDict()
        self._link_capacity = link_capacity
        self._lock = threading.Lock()

    # -- ids -----------------------------------------------------------------

    @staticmethod
    def _new_trace_id() -> str:
        from karpenter_tpu.apis.core import new_uid

        return new_uid()

    @staticmethod
    def _new_span_id() -> str:
        from karpenter_tpu.apis.core import new_uid

        return new_uid()[:16]

    def _sample(self, trace_id: str) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        # stable per-trace decision: a trace is wholly kept or wholly
        # dropped, and the draw is a pure function of the (seeded) trace id
        return int(trace_id[:8], 16) / float(1 << 32) < self.sample_rate

    # -- span lifecycle ------------------------------------------------------

    def start(
        self,
        name: str,
        parent: Any = CURRENT,
        start: Optional[float] = None,
        **attrs: Any,
    ):
        parent_ctx: Optional[SpanContext]
        if parent is CURRENT:
            parent_ctx = current()
        else:
            parent_ctx = parent  # SpanContext or None (explicit root)
        if parent_ctx is not None:
            if not parent_ctx.sampled:
                return _NullSpan(SpanContext(parent_ctx.trace_id, "", False))
            trace_id = parent_ctx.trace_id
            parent_id: Optional[str] = parent_ctx.span_id
        else:
            trace_id = self._new_trace_id()
            parent_id = None
            if not self._sample(trace_id):
                return _NullSpan(SpanContext(trace_id, "", False))
        ctx = SpanContext(trace_id, self._new_span_id(), True)
        return Span(
            name, ctx, parent_id,
            self.clock.now() if start is None else start, **attrs,
        )

    def finish(self, span, end: Optional[float] = None) -> None:
        if isinstance(span, _NullSpan):
            return
        if span.end is None:
            span.end = self.clock.now() if end is None else end
        d = span.to_dict(self.deterministic)
        for exporter in self.exporters:
            exporter.export(d)

    @contextmanager
    def span(
        self,
        name: str,
        parent: Any = CURRENT,
        start: Optional[float] = None,
        **attrs: Any,
    ) -> Iterator[Any]:
        """Open a span, make it the ambient context, export on exit. An
        exception propagating through marks the span failed and re-raises."""
        sp = self.start(name, parent=parent, start=start, **attrs)
        token = _ACTIVE.set(sp.context)
        try:
            yield sp
        except BaseException as e:
            sp.fail(e)
            raise
        finally:
            _ACTIVE.reset(token)
            self.finish(sp)

    def event(
        self,
        name: str,
        parent: Any = CURRENT,
        start: Optional[float] = None,
        error: Optional[BaseException] = None,
        **attrs: Any,
    ):
        """A span opened and finished in one call (instant, or with an
        explicit earlier ``start`` to record a wait that just ended).
        Returns the span so callers can link its context."""
        sp = self.start(name, parent=parent, start=start, **attrs)
        if error is not None:
            sp.fail(error)
        self.finish(sp)
        return sp

    # -- propagation ---------------------------------------------------------

    def carrier(self) -> Optional[dict]:
        """The ambient context as wire-safe carrier fields, or None when
        there is no sampled active span."""
        ctx = current()
        if ctx is None or not ctx.sampled:
            return None
        return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}

    @staticmethod
    def context_from(carrier: Optional[dict]) -> Optional[SpanContext]:
        if not carrier or not carrier.get("trace_id"):
            return None
        return SpanContext(carrier["trace_id"], carrier.get("span_id", ""), True)

    def import_spans(self, dicts) -> int:
        """Re-export span dicts produced elsewhere (the solverd daemon ships
        its spans back in the reply frame so they re-join the caller's
        trace in the caller's exporters)."""
        n = 0
        for d in dicts or ():
            if not isinstance(d, dict) or "trace" not in d:
                continue
            for exporter in self.exporters:
                exporter.export(d)
            n += 1
        return n

    # -- journey links -------------------------------------------------------

    def link(self, kind: str, name: str, ctx) -> None:
        """Remember the span context later hops re-join for this object."""
        if ctx is None or not ctx.sampled:
            return
        with self._lock:
            self._links[(kind, name)] = ctx
            self._links.move_to_end((kind, name))
            while len(self._links) > self._link_capacity:
                self._links.popitem(last=False)

    def linked(self, kind: str, name: str) -> Optional[SpanContext]:
        with self._lock:
            return self._links.get((kind, name))

    def close(self) -> None:
        for exporter in self.exporters:
            close = getattr(exporter, "close", None)
            if close is not None:
                close()
