"""NodeOverlay (v1alpha1): price/capacity overlays on instance types.

Mirrors the reference CRD (pkg/apis/v1alpha1/nodeoverlay.go:29-136 and
nodeoverlay_validation.go): a cluster-scoped object whose requirement
selector picks instance types during scheduling simulations, adjusting
offering prices (fixed override, signed delta, or percentage) and appending
extended capacity resources. Weight orders precedence; application happens
at instance-type fetch in the provisioner, gated on the NodeOverlay feature
flag (operator/options.py FeatureGates).

The reference ships the API surface only (application is provider-side);
here application lives in apply_overlays so the kwok/fake providers and the
solver see adjusted catalogs uniformly.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional, Sequence

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.conditions import ConditionedStatus
from karpenter_tpu.apis.core import ObjectMeta
from karpenter_tpu.cloudprovider.types import InstanceType, Offering, Offerings
from karpenter_tpu.scheduling.requirements import (
    Operator,
    Requirements,
    requirements_from_dicts,
)
from karpenter_tpu.utils.resources import ResourceList

# offering-level keys: a selector on these targets individual offerings, not
# whole instance types
_OFFERING_KEYS = frozenset(
    {wk.LABEL_TOPOLOGY_ZONE, wk.CAPACITY_TYPE_LABEL_KEY}
)

# restricted capacity keys (nodeoverlay.go Capacity CEL rule): overlays add
# EXTENDED resources only
RESTRICTED_CAPACITY = frozenset({"cpu", "memory", "ephemeral-storage", "pods"})

_PRICE_RE = re.compile(r"^\d+(\.\d+)?$")
_ADJUSTMENT_RE = re.compile(
    r"^(([+-](\d*\.?\d+))|(\+\d*\.?\d+%)|(-\d{1,2}(\.\d+)?%)|(-100%))$"
)

CONDITION_VALIDATION_SUCCEEDED = "ValidationSucceeded"


@dataclass
class NodeOverlaySpec:
    # NodeSelectorRequirement dicts ({key, operator, values}) constraining
    # when the overlay applies (well-known or nodepool template labels)
    requirements: list[dict] = field(default_factory=list)
    # "+0.5" / "-1.2" fixed delta, "+10%" / "-15%" percentage, or None
    price_adjustment: Optional[str] = None
    # "1.25" absolute price override (mutually exclusive with adjustment)
    price: Optional[str] = None
    # extended resources appended to matching instance types
    capacity: ResourceList = field(default_factory=dict)
    # higher weight wins; ties merge in reverse-alphabetical name order
    weight: int = 0


@dataclass
class NodeOverlayStatus:
    conditions: list = field(default_factory=list)


@dataclass
class NodeOverlay(ConditionedStatus):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeOverlaySpec = field(default_factory=NodeOverlaySpec)
    status: NodeOverlayStatus = field(default_factory=NodeOverlayStatus)

    KIND = "NodeOverlay"

    def adjusted_price(self, instance_type_price: float) -> float:
        """nodeoverlay.go:107-136: absolute override wins; otherwise apply
        the delta/percentage; never below zero."""
        spec = self.spec
        if spec.price is None and spec.price_adjustment is None:
            return instance_type_price
        if spec.price is not None:
            return float(spec.price)
        adjustment = spec.price_adjustment
        if adjustment.endswith("%"):
            adjusted = instance_type_price * (1 + float(adjustment[:-1]) / 100.0)
        else:
            adjusted = instance_type_price + float(adjustment)
        return adjusted if adjusted >= 0 else 0.0

    def validate(self) -> Optional[str]:
        """Runtime spec validation (nodeoverlay_validation.go + CEL rules)."""
        spec = self.spec
        if spec.price is not None and spec.price_adjustment is not None:
            return "cannot set both 'price' and 'priceAdjustment'"
        if spec.price is not None and not _PRICE_RE.match(spec.price):
            return f"invalid price {spec.price!r}"
        if spec.price_adjustment is not None and not _ADJUSTMENT_RE.match(
            spec.price_adjustment
        ):
            return f"invalid priceAdjustment {spec.price_adjustment!r}"
        if spec.weight and not (1 <= spec.weight <= 10_000):
            return "weight must be in [1, 10000]"
        for key in spec.capacity:
            if key in RESTRICTED_CAPACITY:
                return f"restricted capacity resource {key!r}"
        for req in spec.requirements:
            op = req.get("operator", "")
            values = req.get("values", []) or []
            if op in ("In", "NotIn") and not values:
                return f"requirement {req.get('key')!r} with operator {op!r} must have a value defined"
            if op in ("Gt", "Lt"):
                if len(values) != 1:
                    return f"operator {op!r} requires a single value"
                try:
                    if int(values[0]) < 0:
                        return f"operator {op!r} requires a non-negative integer"
                except ValueError:
                    return f"operator {op!r} requires an integer value"
        return None


def order_by_weight(overlays: Sequence[NodeOverlay]) -> list[NodeOverlay]:
    """nodeoverlay.go:93-105: larger weight first; equal weights order by
    name LATER in the alphabet first (consistent merge order)."""
    return sorted(
        overlays, key=lambda o: (-o.spec.weight, _Rev(o.metadata.name))
    )


class _Rev(str):
    def __lt__(self, other):  # reverse lexicographic
        return str.__gt__(self, other)


def _matches(reqs: Requirements, target: Requirements) -> bool:
    """Strict node-selector semantics over the target's defined labels: In /
    Exists / Gt / Lt fail on undefined keys; NotIn / DoesNotExist pass."""
    for r in reqs:
        if not target.has(r.key):
            # Requirements.get synthesizes Exists for undefined keys; a
            # selector on a label the target doesn't define must not match
            if r.operator in (Operator.IN, Operator.EXISTS, Operator.GT, Operator.LT):
                return False
            continue
        if not target.get(r.key).has_intersection(r):
            return False
    return True


class OverlayApplier:
    """Store-backed, memoized overlay application: adjusted catalogs are
    cached per (overlay versions, nodepool version, catalog identity) so
    downstream id-keyed caches (engine, domain groups) stay warm across
    passes, and the provisioner fetch and provider launch see the SAME
    adjusted prices."""

    def __init__(self, store):
        self.store = store
        self._cache: dict = {}

    def apply(self, node_pool, instance_types) -> list[InstanceType]:
        overlays = self.store.list(NodeOverlay.KIND)
        if not overlays or node_pool is None:
            return list(instance_types)
        key = (
            tuple(
                (o.metadata.uid, o.metadata.resource_version)
                for o in sorted(overlays, key=lambda o: o.metadata.name)
            ),
            node_pool.metadata.uid,
            node_pool.metadata.resource_version,
            tuple(map(id, instance_types)),
        )
        cached = self._cache.get(key)
        if cached is None:
            if len(self._cache) > 64:
                self._cache.clear()
            # hold the source types so their ids can't recycle while cached
            cached = (
                apply_overlays(overlays, node_pool, instance_types),
                list(instance_types),
            )
            self._cache[key] = cached
        return cached[0]


def apply_overlays(
    overlays: Sequence[NodeOverlay],
    node_pool,
    instance_types: Sequence[InstanceType],
) -> list[InstanceType]:
    """Overlay-adjusted copies of `instance_types` for one nodepool.

    Price: for each offering, the highest-weight overlay whose requirements
    match (instance-level labels from the type + nodepool template labels;
    offering-level keys match against the offering) sets the price.
    Capacity: extended resources merge from ALL matching overlays,
    higher-weight values winning per resource. Types nothing matches are
    returned as-is (no copies)."""
    valid = [o for o in overlays if o.validate() is None]
    if not valid:
        return list(instance_types)
    ordered = order_by_weight(valid)
    pool_labels = dict(node_pool.spec.template.labels)
    pool_labels[wk.NODEPOOL_LABEL_KEY] = node_pool.metadata.name
    pool_reqs = Requirements.from_labels(pool_labels)

    split = []
    for o in ordered:
        reqs = requirements_from_dicts(o.spec.requirements)
        inst_rows = Requirements(
            *(r for r in reqs if r.key not in _OFFERING_KEYS)
        )
        offer_rows = Requirements(*(r for r in reqs if r.key in _OFFERING_KEYS))
        split.append((o, inst_rows, offer_rows))

    out: list[InstanceType] = []
    for it in instance_types:
        target = Requirements(*it.requirements.values())
        target.add(*pool_reqs.values())
        matching = [
            (o, offer_rows)
            for o, inst_rows, offer_rows in split
            if _matches(inst_rows, target)
        ]
        if not matching:
            out.append(it)
            continue
        new_offerings = []
        changed = False
        for off in it.offerings:
            priced = None
            for o, offer_rows in matching:
                if offer_rows and not _matches(offer_rows, off.requirements):
                    continue
                if o.spec.price is not None or o.spec.price_adjustment is not None:
                    priced = o
                    break  # highest weight wins
            if priced is None:
                new_offerings.append(off)
                continue
            changed = True
            new_offerings.append(
                Offering(
                    requirements=off.requirements,
                    price=priced.adjusted_price(off.price),
                    available=off.available,
                    reservation_capacity=off.reservation_capacity,
                )
            )
        capacity = dict(it.capacity)
        for o, offer_rows in reversed(matching):  # low weight first: high overwrites
            if offer_rows:
                continue  # offering-scoped overlays don't add node capacity
            for key, value in o.spec.capacity.items():
                if key in RESTRICTED_CAPACITY:
                    continue
                capacity[key] = value
                changed = True
        if not changed:
            out.append(it)
            continue
        out.append(
            InstanceType(
                name=it.name,
                requirements=it.requirements,
                offerings=Offerings(new_offerings),
                capacity=capacity,
                overhead=it.overhead,
            )
        )
    return out
