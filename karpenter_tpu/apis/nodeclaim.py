"""NodeClaim API type (reference pkg/apis/v1/nodeclaim.go:30-78 and
nodeclaim_status.go:25-70)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.apis.conditions import ConditionedStatus
from karpenter_tpu.apis.core import Condition, ObjectMeta, Taint
from karpenter_tpu.utils.resources import ResourceList

# Status condition types (nodeclaim_status.go:26-35)
CONDITION_LAUNCHED = "Launched"
CONDITION_REGISTERED = "Registered"
CONDITION_INITIALIZED = "Initialized"
CONDITION_CONSOLIDATABLE = "Consolidatable"
CONDITION_DRIFTED = "Drifted"
CONDITION_DRAINED = "Drained"
CONDITION_VOLUMES_DETACHED = "VolumesDetached"
CONDITION_INSTANCE_TERMINATING = "InstanceTerminating"
CONDITION_CONSISTENT_STATE_FOUND = "ConsistentStateFound"
CONDITION_DISRUPTION_REASON = "DisruptionReason"
CONDITION_READY = "Ready"

LIVENESS_CONDITIONS = (CONDITION_LAUNCHED, CONDITION_REGISTERED, CONDITION_INITIALIZED)


@dataclass
class NodeClassRef:
    group: str = ""
    kind: str = ""
    name: str = ""


@dataclass
class ResourceRequirements:
    requests: ResourceList = field(default_factory=dict)


@dataclass
class NodeClaimSpec:
    """NodeClaim desired state (nodeclaim.go:30-78)."""

    # NodeSelectorRequirement-shaped dicts with optional minValues
    requirements: list[dict] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    taints: list[Taint] = field(default_factory=list)
    startup_taints: list[Taint] = field(default_factory=list)
    node_class_ref: NodeClassRef = field(default_factory=NodeClassRef)
    termination_grace_period: Optional[float] = None  # seconds
    expire_after: Optional[float] = None  # seconds; None = Never


@dataclass
class NodeClaimStatus:
    provider_id: str = ""
    image_id: str = ""
    node_name: str = ""
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: list[Condition] = field(default_factory=list)
    last_pod_event_time: float = 0.0


@dataclass(eq=False)
class NodeClaim(ConditionedStatus):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeClaimSpec = field(default_factory=NodeClaimSpec)
    status: NodeClaimStatus = field(default_factory=NodeClaimStatus)

    KIND = "NodeClaim"
