"""Shared status-condition helpers for API objects whose status carries a
list[Condition] (NodeClaim, NodePool, NodeOverlay). One implementation so
transition-time bumping stays consistent (reference: operatorpkg status
conditions), and the single chokepoint where the per-CRD condition metrics
the reference auto-emits (controllers.go:102-120) are recorded: every status
flip increments the transitions counter and — when the condition had a prior
transition time — observes how long the previous status was held."""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.apis.core import Condition
from karpenter_tpu.metrics import global_registry

CONDITION_TRANSITIONS_TOTAL = global_registry.counter(
    "karpenter_status_condition_transitions_total",
    "status-condition transitions per kind/type/status",
    labels=["kind", "type", "status"],
)
CONDITION_TRANSITION_SECONDS = global_registry.histogram(
    "karpenter_status_condition_transition_seconds",
    "time a condition held its previous status before transitioning",
    labels=["kind", "type", "status"],
)


class ConditionedStatus:
    """Mixin for objects exposing `.status.conditions: list[Condition]`."""

    def get_condition(self, condition_type: str) -> Optional[Condition]:
        for c in self.status.conditions:
            if c.type == condition_type:
                return c
        return None

    def _record_transition(
        self, condition_type: str, status: str, held_for: Optional[float]
    ) -> None:
        kind = getattr(self, "KIND", type(self).__name__)
        labels = {"kind": kind, "type": condition_type, "status": status}
        CONDITION_TRANSITIONS_TOTAL.inc(labels)
        if held_for is not None and held_for >= 0.0:
            CONDITION_TRANSITION_SECONDS.observe(held_for, labels)

    def set_condition(
        self,
        condition_type: str,
        status: str,
        reason: str = "",
        message: str = "",
        now: float = 0.0,
    ) -> Condition:
        existing = self.get_condition(condition_type)
        if existing is not None:
            if existing.status != status:
                self._record_transition(
                    condition_type, status, now - existing.last_transition_time
                )
                existing.last_transition_time = now
            existing.status = status
            existing.reason = reason
            existing.message = message
            return existing
        self._record_transition(condition_type, status, None)
        c = Condition(
            type=condition_type,
            status=status,
            reason=reason,
            message=message,
            last_transition_time=now,
        )
        self.status.conditions.append(c)
        return c

    def clear_condition(self, condition_type: str) -> None:
        self.status.conditions = [
            c for c in self.status.conditions if c.type != condition_type
        ]

    def condition_is_true(self, condition_type: str) -> bool:
        c = self.get_condition(condition_type)
        return c is not None and c.status == "True"
