"""Shared status-condition helpers for API objects whose status carries a
list[Condition] (NodeClaim, NodePool). One implementation so transition-time
bumping stays consistent (reference: operatorpkg status conditions)."""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.apis.core import Condition


class ConditionedStatus:
    """Mixin for objects exposing `.status.conditions: list[Condition]`."""

    def get_condition(self, condition_type: str) -> Optional[Condition]:
        for c in self.status.conditions:
            if c.type == condition_type:
                return c
        return None

    def set_condition(
        self,
        condition_type: str,
        status: str,
        reason: str = "",
        message: str = "",
        now: float = 0.0,
    ) -> Condition:
        existing = self.get_condition(condition_type)
        if existing is not None:
            if existing.status != status:
                existing.last_transition_time = now
            existing.status = status
            existing.reason = reason
            existing.message = message
            return existing
        c = Condition(
            type=condition_type,
            status=status,
            reason=reason,
            message=message,
            last_transition_time=now,
        )
        self.status.conditions.append(c)
        return c

    def clear_condition(self, condition_type: str) -> None:
        self.status.conditions = [
            c for c in self.status.conditions if c.type != condition_type
        ]

    def condition_is_true(self, condition_type: str) -> bool:
        c = self.get_condition(condition_type)
        return c is not None and c.status == "True"
