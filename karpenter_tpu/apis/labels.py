"""Well-known labels, annotations, taints and normalization tables.

Mirrors the semantics of the reference's pkg/apis/v1/labels.go:32-183 and
pkg/apis/v1/taints.go (constants only — the representation is our own).
"""

from __future__ import annotations

GROUP = "karpenter.sh"
COMPATIBILITY_GROUP = "compatibility." + GROUP

# Well known values (reference labels.go:32-38)
ARCHITECTURE_AMD64 = "amd64"
ARCHITECTURE_ARM64 = "arm64"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_RESERVED = "reserved"

# Kubernetes upstream label keys we depend on
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_TOPOLOGY_ZONE = "topology.kubernetes.io/zone"
LABEL_TOPOLOGY_REGION = "topology.kubernetes.io/region"
LABEL_INSTANCE_TYPE = "node.kubernetes.io/instance-type"
LABEL_ARCH = "kubernetes.io/arch"
LABEL_OS = "kubernetes.io/os"
LABEL_WINDOWS_BUILD = "node.kubernetes.io/windows-build"

# Karpenter-specific domains and labels (reference labels.go:41-47)
NODEPOOL_LABEL_KEY = GROUP + "/nodepool"
NODE_INITIALIZED_LABEL_KEY = GROUP + "/initialized"
NODE_REGISTERED_LABEL_KEY = GROUP + "/registered"
NODE_DO_NOT_SYNC_TAINTS_LABEL_KEY = GROUP + "/do-not-sync-taints"
CAPACITY_TYPE_LABEL_KEY = GROUP + "/capacity-type"

# Karpenter-specific annotations (reference labels.go:50-57)
DO_NOT_DISRUPT_ANNOTATION_KEY = GROUP + "/do-not-disrupt"
PROVIDER_COMPATIBILITY_ANNOTATION_KEY = COMPATIBILITY_GROUP + "/provider"
NODEPOOL_HASH_ANNOTATION_KEY = GROUP + "/nodepool-hash"
NODEPOOL_HASH_VERSION_ANNOTATION_KEY = GROUP + "/nodepool-hash-version"
NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY = GROUP + "/nodeclaim-termination-timestamp"
NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY = GROUP + "/nodeclaim-min-values-relaxed"

# Finalizers (reference labels.go:60-62)
TERMINATION_FINALIZER = GROUP + "/termination"

# Taint keys (reference pkg/apis/v1/taints.go)
DISRUPTED_TAINT_KEY = GROUP + "/disrupted"
UNREGISTERED_TAINT_KEY = GROUP + "/unregistered"

# Upstream taint keys recognised as ephemeral during node startup
TAINT_NODE_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_NODE_UNREACHABLE = "node.kubernetes.io/unreachable"
TAINT_EXTERNAL_CLOUD_PROVIDER = "node.cloudprovider.kubernetes.io/uninitialized"

# Well-known resource names
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"
RESOURCE_PODS = "pods"

WELL_KNOWN_RESOURCES = frozenset(
    {RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE, RESOURCE_PODS}
)

# Restricted domains: prohibited by kubelet or reserved (reference labels.go:66-70)
RESTRICTED_LABEL_DOMAINS = frozenset({"kubernetes.io", "k8s.io", GROUP})

# Sub-domains of restricted domains that are allowed (reference labels.go:74-78)
LABEL_DOMAIN_EXCEPTIONS = frozenset(
    {"kops.k8s.io", "node.kubernetes.io", "node-restriction.kubernetes.io"}
)

# Restricted-domain labels Karpenter understands and allows (reference labels.go:83-92)
# Uniquely identifies a capacity reservation on a reserved offering. The
# reference leaves this provider-overridable and its in-tree providers
# register it as well-known (cloudprovider/types.go:44-49,
# fake/cloudprovider.go:45) — without that, no claim could ever be
# compatible with a reserved offering's requirements.
RESERVATION_ID_LABEL_KEY = GROUP + "/reservation-id"

WELL_KNOWN_LABELS = frozenset(
    {
        NODEPOOL_LABEL_KEY,
        LABEL_TOPOLOGY_ZONE,
        LABEL_TOPOLOGY_REGION,
        LABEL_INSTANCE_TYPE,
        LABEL_ARCH,
        LABEL_OS,
        CAPACITY_TYPE_LABEL_KEY,
        LABEL_WINDOWS_BUILD,
        RESERVATION_ID_LABEL_KEY,
    }
)

WELL_KNOWN_VALUES_FOR_REQUIREMENTS = {
    CAPACITY_TYPE_LABEL_KEY: frozenset(
        {CAPACITY_TYPE_ON_DEMAND, CAPACITY_TYPE_SPOT, CAPACITY_TYPE_RESERVED}
    )
}

# Labels that must never be injected onto nodes (reference labels.go:116-118)
RESTRICTED_LABELS = frozenset({LABEL_HOSTNAME})

# Aliased/legacy label keys normalized into well-known ones (reference labels.go:122-129)
NORMALIZED_LABELS = {
    "failure-domain.beta.kubernetes.io/zone": LABEL_TOPOLOGY_ZONE,
    "failure-domain.beta.kubernetes.io/region": LABEL_TOPOLOGY_REGION,
    "beta.kubernetes.io/arch": LABEL_ARCH,
    "beta.kubernetes.io/os": LABEL_OS,
    "beta.kubernetes.io/instance-type": LABEL_INSTANCE_TYPE,
}


def get_label_domain(key: str) -> str:
    if "/" in key:
        return key.split("/", 1)[0]
    return ""


def is_restricted_node_label(key: str) -> bool:
    """True if a node label should not be injected by the provisioner.

    Mirrors reference labels.go:156-172.
    """
    if key in WELL_KNOWN_LABELS:
        return True
    domain = get_label_domain(key)
    for exception in LABEL_DOMAIN_EXCEPTIONS:
        if domain == exception or domain.endswith("." + exception):
            return False
    for restricted in RESTRICTED_LABEL_DOMAINS:
        if domain == restricted or domain.endswith("." + restricted):
            return True
    return key in RESTRICTED_LABELS


def is_restricted_label(key: str) -> str | None:
    """Returns an error string if the label is restricted (labels.go:132-140)."""
    if key in WELL_KNOWN_LABELS:
        return None
    if is_restricted_node_label(key):
        return (
            f"label {key} is restricted; specify a well known label "
            f"or a custom label that does not use a restricted domain"
        )
    return None
