"""NodePool API type (reference pkg/apis/v1/nodepool.go:39-276)."""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.apis.conditions import ConditionedStatus
from karpenter_tpu.apis.core import ObjectMeta
from karpenter_tpu.apis.nodeclaim import NodeClaimSpec
from karpenter_tpu.utils.resources import ResourceList

CONSOLIDATION_POLICY_WHEN_EMPTY = "WhenEmpty"
CONSOLIDATION_POLICY_WHEN_EMPTY_OR_UNDERUTILIZED = "WhenEmptyOrUnderutilized"

DISRUPTION_REASON_UNDERUTILIZED = "Underutilized"
DISRUPTION_REASON_EMPTY = "Empty"
DISRUPTION_REASON_DRIFTED = "Drifted"

NODEPOOL_HASH_VERSION = "v1"

# NodePool status conditions (nodepool_status.go:24-52)
CONDITION_VALIDATION_SUCCEEDED = "ValidationSucceeded"
CONDITION_NODECLASS_READY = "NodeClassReady"
CONDITION_NODE_REGISTRATION_HEALTHY = "NodeRegistrationHealthy"
CONDITION_READY = "Ready"


@dataclass
class Budget:
    """Max simultaneously-disrupting nodes, optionally cron-windowed
    (nodepool.go:90-122)."""

    nodes: str = "10%"  # int string or percentage
    reasons: list[str] = field(default_factory=list)  # empty = all reasons
    schedule: Optional[str] = None  # cron; None = always active
    duration: Optional[float] = None  # seconds; required with schedule

    def allowed_disruptions(self, total_nodes: int, now: float) -> int:
        """Resolve the budget to a node count at `now` (inactive = unlimited).

        Percentages round UP so a small nodepool is never permanently
        blocked by the default 10% budget (reference nodepool.go:333-338);
        a schedule without a duration is invalid and fails closed
        (nodepool.go:324-329).
        """
        if self.schedule is not None and self.duration is None:
            return 0
        if not self.is_active(now):
            return total_nodes  # no restriction from an inactive budget
        if self.nodes.endswith("%"):
            pct = int(self.nodes[:-1])
            return int(math.ceil(total_nodes * pct / 100.0))
        return int(self.nodes)

    def is_active(self, now: float) -> bool:
        if self.schedule is None:
            return True
        from karpenter_tpu.utils.cron import last_fire_time

        start = last_fire_time(self.schedule, now)
        if start is None:
            return False
        return now - start < (self.duration or 0.0)


@dataclass
class Disruption:
    consolidate_after: Optional[float] = 0.0  # seconds; None = Never
    consolidation_policy: str = CONSOLIDATION_POLICY_WHEN_EMPTY_OR_UNDERUTILIZED
    budgets: list[Budget] = field(default_factory=lambda: [Budget(nodes="10%")])


@dataclass
class NodeClaimTemplate:
    """Template stamped onto launched NodeClaims (nodepool.go:141-186)."""

    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    spec: NodeClaimSpec = field(default_factory=NodeClaimSpec)


@dataclass
class NodePoolSpec:
    template: NodeClaimTemplate = field(default_factory=NodeClaimTemplate)
    disruption: Disruption = field(default_factory=Disruption)
    limits: ResourceList = field(default_factory=dict)
    weight: int = 0


@dataclass
class NodePoolStatus:
    resources: ResourceList = field(default_factory=dict)
    node_count: int = 0
    conditions: list = field(default_factory=list)


@dataclass(eq=False)
class NodePool(ConditionedStatus):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodePoolSpec = field(default_factory=NodePoolSpec)
    status: NodePoolStatus = field(default_factory=NodePoolStatus)

    KIND = "NodePool"

    def static_hash(self) -> str:
        """Hash of drift-relevant static fields (nodepool.go hash tags:
        everything under template except ignored fields; reference
        nodepool/hash controller)."""
        spec = self.spec.template.spec
        payload = {
            "labels": self.spec.template.labels,
            "annotations": self.spec.template.annotations,
            "taints": [(t.key, t.value, t.effect) for t in spec.taints],
            "startup_taints": [(t.key, t.value, t.effect) for t in spec.startup_taints],
            "node_class_ref": (
                spec.node_class_ref.group,
                spec.node_class_ref.kind,
                spec.node_class_ref.name,
            ),
            "expire_after": spec.expire_after,
            "termination_grace_period": spec.termination_grace_period,
        }
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True, default=str).encode()
        ).hexdigest()[:16]

    def allowed_disruptions(self, reason: str, total_nodes: int, now: float) -> int:
        """Most-restrictive active budget for the reason (nodepool.go:61-68)."""
        allowed = total_nodes
        for budget in self.spec.disruption.budgets:
            if budget.reasons and reason not in budget.reasons:
                continue
            allowed = min(allowed, budget.allowed_disruptions(total_nodes, now))
        return allowed
