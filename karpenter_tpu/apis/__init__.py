"""API surface: the data model equivalent of the reference's pkg/apis CRDs."""
