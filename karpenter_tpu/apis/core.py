"""Core object model: the subset of Kubernetes core/v1 shapes the framework
consumes, as plain dataclasses (no apimachinery).

These mirror the fields the reference reads from corev1 objects (Pod spec
scheduling fields, Node capacity/taints, PDBs, DaemonSets); everything else
is intentionally omitted.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from karpenter_tpu.utils import resources as r
from karpenter_tpu.utils.resources import ResourceList


# Process-wide uid source. Production uses uuid4; the simulator installs a
# seeded random.Random so generated names/uids — and therefore event-log
# digests — are identical across runs with the same seed.
_uid_rng = None


def set_uid_source(rng) -> None:
    """Install a ``random.Random`` (or None to restore uuid4) as the uid
    source. Deterministic ids are a simulation concern only — never set
    this in a live operator."""
    global _uid_rng
    _uid_rng = rng


def new_uid() -> str:
    if _uid_rng is not None:
        return f"{_uid_rng.getrandbits(128):032x}"
    return uuid.uuid4().hex


@dataclass
class OwnerReference:
    kind: str
    name: str
    uid: str
    controller: bool = False
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)
    owner_references: list[OwnerReference] = field(default_factory=list)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0
    generation: int = 1


@dataclass
class Condition:
    type: str
    status: str  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0
    observed_generation: int = 0


# -- taints / tolerations ---------------------------------------------------

NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"


@dataclass(frozen=True)
class Taint:
    key: str
    effect: str = NO_SCHEDULE
    value: str = ""

    def match(self, other: "Taint") -> bool:
        return self.key == other.key and self.effect == other.effect


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # "Equal" | "Exists"
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """Mirrors corev1.Toleration.ToleratesTaint: unknown operators never
        tolerate, and Exists requires an empty value."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator in ("", "Equal"):
            return self.value == taint.value
        if self.operator == "Exists":
            return self.value == ""
        return False


# -- pod --------------------------------------------------------------------


@dataclass
class ContainerPort:
    container_port: int
    host_port: int = 0
    host_ip: str = ""
    protocol: str = "TCP"


@dataclass
class Container:
    name: str = "main"
    requests: ResourceList = field(default_factory=dict)
    limits: ResourceList = field(default_factory=dict)
    ports: list[ContainerPort] = field(default_factory=list)
    restart_policy: Optional[str] = None  # "Always" => sidecar init container


@dataclass
class LabelSelector:
    match_labels: dict[str, str] = field(default_factory=dict)
    # list of dicts: {"key","operator","values"}
    match_expressions: list[dict] = field(default_factory=list)

    def matches(self, labels: dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for expr in self.match_expressions:
            key, op = expr["key"], expr["operator"]
            values = expr.get("values", [])
            actual = labels.get(key)
            if op == "In":
                if actual is None or actual not in values:
                    return False
            elif op == "NotIn":
                if actual is not None and actual in values:
                    return False
            elif op == "Exists":
                if actual is None:
                    return False
            elif op == "DoesNotExist":
                if actual is not None:
                    return False
            else:
                raise ValueError(f"unknown selector operator {op}")
        return True


@dataclass
class NodeSelectorTerm:
    # list of dicts: {"key","operator","values"}
    match_expressions: list[dict] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass
class NodeAffinity:
    required: list[NodeSelectorTerm] = field(default_factory=list)
    preferred: list[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    topology_key: str
    label_selector: Optional[LabelSelector] = None
    namespaces: list[str] = field(default_factory=list)
    namespace_selector: Optional[LabelSelector] = None


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm


@dataclass
class PodAffinity:
    required: list[PodAffinityTerm] = field(default_factory=list)
    preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required: list[PodAffinityTerm] = field(default_factory=list)
    preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # "DoNotSchedule" | "ScheduleAnyway"
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None
    node_affinity_policy: Optional[str] = None  # "Honor" | "Ignore"; None = Honor
    node_taints_policy: Optional[str] = None  # "Honor" | "Ignore"; None = Ignore
    match_label_keys: list[str] = field(default_factory=list)


@dataclass
class Volume:
    name: str = ""
    persistent_volume_claim: Optional[str] = None  # claim name
    ephemeral_storage_class: Optional[str] = None  # generic ephemeral volume


@dataclass
class PodSpec:
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: list[Toleration] = field(default_factory=list)
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    topology_spread_constraints: list[TopologySpreadConstraint] = field(
        default_factory=list
    )
    volumes: list[Volume] = field(default_factory=list)
    node_name: str = ""
    scheduler_name: str = "default-scheduler"
    priority: Optional[int] = None
    priority_class_name: str = ""
    preemption_policy: str = "PreemptLowerPriority"
    overhead: ResourceList = field(default_factory=dict)
    termination_grace_period_seconds: Optional[int] = 30
    scheduling_gates: list[str] = field(default_factory=list)
    host_network: bool = False


@dataclass
class PodCondition(Condition):
    pass


@dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    conditions: list[Condition] = field(default_factory=list)
    nominated_node_name: str = ""


@dataclass(eq=False)
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    KIND = "Pod"


# -- node -------------------------------------------------------------------


@dataclass
class NodeSpec:
    provider_id: str = ""
    taints: list[Taint] = field(default_factory=list)
    unschedulable: bool = False


@dataclass
class NodeStatus:
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: list[Condition] = field(default_factory=list)
    phase: str = ""


@dataclass(eq=False)
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    KIND = "Node"


# -- workloads / policies ---------------------------------------------------


@dataclass
class DaemonSetSpec:
    selector: LabelSelector = field(default_factory=LabelSelector)
    template_metadata: ObjectMeta = field(default_factory=ObjectMeta)
    template_spec: PodSpec = field(default_factory=PodSpec)


@dataclass(eq=False)
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)

    KIND = "DaemonSet"


@dataclass
class PodDisruptionBudgetSpec:
    selector: LabelSelector = field(default_factory=LabelSelector)
    min_available: Optional[int | str] = None  # int or percentage string
    max_unavailable: Optional[int | str] = None
    unhealthy_pod_eviction_policy: Optional[str] = None  # "AlwaysAllow" | None


@dataclass
class PodDisruptionBudgetStatus:
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0


@dataclass(eq=False)
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    status: PodDisruptionBudgetStatus = field(default_factory=PodDisruptionBudgetStatus)

    KIND = "PodDisruptionBudget"


# -- storage (volume topology) ---------------------------------------------


@dataclass(eq=False)
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = "WaitForFirstConsumer"
    # NodeSelectorTerm-shaped allowed topologies
    allowed_topologies: list[NodeSelectorTerm] = field(default_factory=list)

    KIND = "StorageClass"


@dataclass(eq=False)
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class_name: Optional[str] = None
    volume_name: str = ""  # bound PV name
    phase: str = "Pending"

    KIND = "PersistentVolumeClaim"


@dataclass(eq=False)
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    node_affinity_required: list[NodeSelectorTerm] = field(default_factory=list)
    csi_driver: str = ""

    KIND = "PersistentVolume"


@dataclass
class CSINodeDriver:
    name: str
    allocatable_count: Optional[int] = None


@dataclass(eq=False)
class CSINode:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    drivers: list[CSINodeDriver] = field(default_factory=list)

    KIND = "CSINode"


@dataclass(eq=False)
class VolumeAttachment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    attacher: str = ""
    node_name: str = ""
    pv_name: str = ""

    KIND = "VolumeAttachment"


@dataclass(eq=False)
class Namespace:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    KIND = "Namespace"


# -- helpers ----------------------------------------------------------------


def _effective_requests(container: Container) -> ResourceList:
    """Container requests with limits defaulted in for resources that set no
    request (k8s admission semantics; reference pkg/utils/resources
    MergeResourceLimitsIntoRequests)."""
    out = dict(container.requests)
    for k, v in container.limits.items():
        if k not in out:
            out[k] = v
    return out


def pod_resource_requests(pod: Pod) -> ResourceList:
    """Effective pod resource requests per the k8s pod-resource model:

    max( sum(app containers) + sum(sidecar inits),
         max_i(init_i + sum(sidecars started before init_i)) ) + overhead

    where "Always"-restart init containers are sidecars that keep running
    alongside later init containers and the app. Mirrors the accounting in
    the reference's pkg/utils/resources (Ceiling/podRequests).
    """
    sidecar_sum: ResourceList = {}
    init_ceiling: ResourceList = {}
    for c in pod.spec.init_containers:
        if c.restart_policy == "Always":
            sidecar_sum = r.merge(sidecar_sum, _effective_requests(c))
        else:
            init_ceiling = r.max_resources(
                init_ceiling, r.merge(_effective_requests(c), sidecar_sum)
            )
    main = r.merge(sidecar_sum, *(_effective_requests(c) for c in pod.spec.containers))
    out = r.max_resources(main, init_ceiling)
    if pod.spec.overhead:
        out = r.merge(out, pod.spec.overhead)
    out["pods"] = out.get("pods", 0.0) + 1.0
    return out


def pod_resource_limits(pod: Pod) -> ResourceList:
    """Effective pod resource limits under the same ceiling model as
    requests (reference pkg/utils/resources PodLimits — resources without a
    limit contribute nothing)."""
    sidecar_sum: ResourceList = {}
    init_ceiling: ResourceList = {}
    for c in pod.spec.init_containers:
        if c.restart_policy == "Always":
            sidecar_sum = r.merge(sidecar_sum, c.limits)
        else:
            init_ceiling = r.max_resources(
                init_ceiling, r.merge(c.limits, sidecar_sum)
            )
    main = r.merge(sidecar_sum, *(c.limits for c in pod.spec.containers))
    out = r.max_resources(main, init_ceiling)
    if pod.spec.overhead:
        out = r.merge(out, pod.spec.overhead)
    return out
