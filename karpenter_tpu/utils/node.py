"""Node helpers (reference pkg/utils/node)."""

from __future__ import annotations

from typing import Any, Optional


def claim_for_node(store, node) -> Optional[Any]:
    """The NodeClaim owning a node, matched by provider id
    (pkg/utils/nodeclaim NodeClaimForNode) — the one lookup shared by the
    termination, health, GC, and hydration controllers."""
    pid = node.spec.provider_id
    if not pid:
        return None
    return next(
        iter(
            store.list(
                "NodeClaim",
                predicate=lambda c: c.status.provider_id == pid,
            )
        ),
        None,
    )
