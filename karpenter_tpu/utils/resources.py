"""Resource-list arithmetic over plain dicts of float.

The equivalent of the reference's pkg/utils/resources (Fits/Merge/Subtract/
Cmp over corev1.ResourceList). Quantities are floats in canonical units:
cpu in cores, memory/storage in bytes, pods/extended resources in counts.
`parse_quantity` accepts Kubernetes quantity strings ("100m", "1Gi").
"""

from __future__ import annotations

import re
from typing import Iterable, Mapping

ResourceList = dict[str, float]

_DECIMAL_SUFFIXES = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "": 1.0,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}
_BINARY_SUFFIXES = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
_QUANTITY_RE = re.compile(r"^([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)([A-Za-z]*)$")


def parse_quantity(value: str | int | float) -> float:
    """Parse a Kubernetes quantity string into a float in canonical units."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _QUANTITY_RE.match(value.strip())
    if not m:
        raise ValueError(f"invalid quantity {value!r}")
    number, suffix = m.groups()
    if suffix in _BINARY_SUFFIXES:
        return float(number) * _BINARY_SUFFIXES[suffix]
    if suffix in _DECIMAL_SUFFIXES:
        return float(number) * _DECIMAL_SUFFIXES[suffix]
    raise ValueError(f"invalid quantity suffix {suffix!r} in {value!r}")


def parse_resource_list(raw: Mapping[str, str | int | float]) -> ResourceList:
    return {k: parse_quantity(v) for k, v in raw.items()}


def merge(*resource_lists: Mapping[str, float]) -> ResourceList:
    """Element-wise sum; missing keys are zero (reference resources.Merge)."""
    out: ResourceList = {}
    for rl in resource_lists:
        for k, v in rl.items():
            out[k] = out.get(k, 0.0) + v
    return out


def subtract(a: Mapping[str, float], b: Mapping[str, float]) -> ResourceList:
    """a - b over a's keys ONLY (reference resources.Subtract keeps LHS keys
    — a nodepool with no limits stays unlimited after subtracting usage)."""
    return {k: v - b.get(k, 0.0) for k, v in a.items()}


def subtract_into(a: Mapping[str, float], b: Mapping[str, float]) -> ResourceList:
    """a - b over the union of keys (reference resources.SubtractFrom)."""
    out: ResourceList = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) - v
    return out


def scale(rl: Mapping[str, float], factor: float) -> ResourceList:
    return {k: v * factor for k, v in rl.items()}


def fits(candidate: Mapping[str, float], total: Mapping[str, float]) -> bool:
    """True if every requested resource fits in `total`.

    Missing keys in `total` are zero, so a request for an extended resource
    the node doesn't expose fails (reference resources.Fits semantics).
    """
    return all(v <= total.get(k, 0.0) + 1e-9 for k, v in candidate.items() if v > 0)


def cmp(a: Mapping[str, float], b: Mapping[str, float]) -> bool:
    """True if a <= b element-wise over a's keys."""
    return fits(a, b)


def max_resources(*resource_lists: Mapping[str, float]) -> ResourceList:
    """Element-wise max (used for init-container request folding)."""
    out: ResourceList = {}
    for rl in resource_lists:
        for k, v in rl.items():
            out[k] = max(out.get(k, 0.0), v)
    return out


def is_zero(rl: Mapping[str, float]) -> bool:
    return all(abs(v) < 1e-12 for v in rl.values())


def non_negative(rl: Mapping[str, float]) -> ResourceList:
    return {k: max(0.0, v) for k, v in rl.items()}


def keys(*resource_lists: Mapping[str, float]) -> set[str]:
    out: set[str] = set()
    for rl in resource_lists:
        out.update(rl.keys())
    return out


def format_cpu(cores: float) -> str:
    if cores == int(cores):
        return str(int(cores))
    return f"{int(round(cores * 1000))}m"


def format_memory(num_bytes: float) -> str:
    for suffix, mult in (("Gi", 2**30), ("Mi", 2**20), ("Ki", 2**10)):
        if num_bytes >= mult and num_bytes % mult == 0:
            return f"{int(num_bytes // mult)}{suffix}"
    return str(int(num_bytes))
