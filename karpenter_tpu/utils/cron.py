"""Minimal cron-schedule evaluation for disruption budget windows.

Supports standard 5-field cron (minute hour day-of-month month day-of-week)
plus the @hourly/@daily/@midnight/@weekly/@monthly/@yearly aliases — the
subset the reference accepts for NodePool budgets (nodepool.go:99-106,
upstream cronjob syntax, UTC, no timezones).
"""

from __future__ import annotations

from datetime import datetime, timedelta, timezone
from typing import Optional

_ALIASES = {
    "@hourly": "0 * * * *",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@weekly": "0 0 * * 0",
    "@monthly": "0 0 1 * *",
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
}

_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]

_MONTH_NAMES = {
    name: i + 1
    for i, name in enumerate(
        ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug", "sep", "oct", "nov", "dec"]
    )
}
_DOW_NAMES = {name: i for i, name in enumerate(["sun", "mon", "tue", "wed", "thu", "fri", "sat"])}


class CronError(ValueError):
    pass


def _parse_field(field: str, lo: int, hi: int, names: dict[str, int]) -> tuple[set[int], bool]:
    """Returns (allowed values, is_wildcard)."""
    out: set[int] = set()
    wildcard = False
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step <= 0:
                raise CronError(f"invalid step in {field!r}")
        if part in ("*", "?"):
            wildcard = wildcard or step == 1
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = _value(a, names), _value(b, names)
        else:
            start = end = _value(part, names)
            if step > 1:
                end = hi
        if not (lo <= start <= hi and lo <= end <= hi and start <= end):
            raise CronError(f"field {field!r} out of range [{lo},{hi}]")
        out.update(range(start, end + 1, step))
    return out, wildcard


def _value(token: str, names: dict[str, int]) -> int:
    token = token.strip().lower()
    if token in names:
        return names[token]
    v = int(token)
    if names is _DOW_NAMES and v == 7:  # both 0 and 7 are Sunday
        return 0
    return v


class Schedule:
    def __init__(self, expr: str):
        expr = _ALIASES.get(expr.strip(), expr.strip())
        fields = expr.split()
        if len(fields) != 5:
            raise CronError(f"expected 5 cron fields, got {len(fields)} in {expr!r}")
        self.minutes, _ = _parse_field(fields[0], 0, 59, {})
        self.hours, _ = _parse_field(fields[1], 0, 23, {})
        self.dom, self.dom_wild = _parse_field(fields[2], 1, 31, {})
        self.months, _ = _parse_field(fields[3], 1, 12, _MONTH_NAMES)
        self.dow, self.dow_wild = _parse_field(fields[4], 0, 6, _DOW_NAMES)

    def _day_matches(self, dt: datetime) -> bool:
        dom_ok = dt.day in self.dom
        # cron dow: 0=Sunday; python weekday(): 0=Monday
        dow_ok = ((dt.weekday() + 1) % 7) in self.dow
        # standard cron: if both dom and dow are restricted, OR them
        if not self.dom_wild and not self.dow_wild:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def last_fire(self, now: float) -> Optional[float]:
        """Most recent fire time <= now, or None within a 2-year lookback."""
        dt = datetime.fromtimestamp(now, tz=timezone.utc).replace(second=0, microsecond=0)
        day = dt
        for i in range(366 * 2):
            if day.month in self.months and self._day_matches(day):
                max_h = dt.hour if i == 0 else 23
                for h in sorted((x for x in self.hours if x <= max_h), reverse=True):
                    max_m = dt.minute if (i == 0 and h == dt.hour) else 59
                    ms = [x for x in self.minutes if x <= max_m]
                    if ms:
                        fire = day.replace(hour=h, minute=max(ms))
                        return fire.timestamp()
            day = (day - timedelta(days=1)).replace(hour=23, minute=59)
        return None


def last_fire_time(schedule: str, now: float) -> Optional[float]:
    return Schedule(schedule).last_fire(now)


def validate(schedule: str) -> Optional[str]:
    try:
        Schedule(schedule)
        return None
    except (CronError, ValueError) as e:
        return str(e)
