"""Injectable clock, mirroring the reference's use of k8s.io/utils/clock.

Every controller takes a Clock so tests can drive time deterministically
(reference test pattern: clock.NewFakeClock in every suite_test.go).
"""

from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        return time.time()

    def since(self, t: float) -> float:
        return self.now() - t

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    """Settable clock for tests (k8s.io/utils/clock/testing.FakeClock).

    Two sleep disciplines share one time source:

    - Default (controller tests): ``sleep`` advances virtual time itself —
      the sleeping code IS the thing driving time, so it steps and returns.
    - Driver mode (``enable_blocking_sleep``): one thread — the simulator's
      event loop — owns time. ``sleep`` called from the driver still steps
      (it would otherwise deadlock against itself), but ``sleep`` from any
      OTHER thread registers a waiter and blocks until the driver advances
      virtual time past its deadline. No busy-waiting: waiters park on a
      condition variable that ``step``/``set_time`` notify.
    """

    def __init__(self, start: float = 1_000_000.0):
        self._now = start
        self._cond = threading.Condition()
        self._driver: threading.Thread | None = None
        # deadlines of currently-blocked sleepers, for introspection: the
        # simulator can advance straight to the earliest wakeup
        self._waiters: list[float] = []

    def now(self) -> float:
        return self._now

    def __getstate__(self) -> dict:
        # The condition variable, driver thread, and parked waiters are
        # process-local runtime state. A pickled clock travels as just its
        # current time — the socket transport ships schedulers that embed
        # their clock, and the receiving daemon gets a fresh, idle one.
        return {"_now": self._now}

    def __setstate__(self, state: dict) -> None:
        self._now = state["_now"]
        self._cond = threading.Condition()
        self._driver = None
        self._waiters = []

    def enable_blocking_sleep(self, driver: threading.Thread | None = None) -> None:
        """Worker-thread sleeps now block until virtual time passes. The
        driver thread (default: the caller's) keeps step-on-sleep semantics
        so the thread advancing time can never deadlock on itself."""
        with self._cond:
            self._driver = driver or threading.current_thread()

    def disable_blocking_sleep(self) -> None:
        with self._cond:
            self._driver = None
            self._cond.notify_all()

    def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            return
        with self._cond:
            if self._driver is None or self._driver is threading.current_thread():
                self._advance(seconds)
                return
            deadline = self._now + seconds
            self._waiters.append(deadline)
            try:
                while self._now < deadline and self._driver is not None:
                    self._cond.wait()
            finally:
                self._waiters.remove(deadline)

    def step(self, seconds: float) -> None:
        with self._cond:
            self._advance(seconds)

    def set_time(self, t: float) -> None:
        with self._cond:
            self._now = t
            self._cond.notify_all()

    def _advance(self, seconds: float) -> None:
        self._now += seconds
        self._cond.notify_all()

    # -- waiter introspection (simulator event loop) ------------------------

    def waiter_count(self) -> int:
        with self._cond:
            return len(self._waiters)

    def next_wakeup(self) -> float | None:
        """Earliest blocked sleeper's deadline, or None."""
        with self._cond:
            return min(self._waiters) if self._waiters else None
