"""Injectable clock, mirroring the reference's use of k8s.io/utils/clock.

Every controller takes a Clock so tests can drive time deterministically
(reference test pattern: clock.NewFakeClock in every suite_test.go).
"""

from __future__ import annotations

import time


class Clock:
    def now(self) -> float:
        return time.time()

    def since(self, t: float) -> float:
        return self.now() - t

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    """Settable clock for tests (k8s.io/utils/clock/testing.FakeClock)."""

    def __init__(self, start: float = 1_000_000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        self.step(seconds)

    def step(self, seconds: float) -> None:
        self._now += seconds

    def set_time(self, t: float) -> None:
        self._now = t
