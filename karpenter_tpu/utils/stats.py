"""Shared order statistics.

One implementation of nearest-rank percentile, used by both the sim's
accounting report and the tracing journey stats — the two surfaces quote
percentiles over the same journeys and must never disagree on rank
rounding.
"""

from __future__ import annotations

import math
from typing import Optional


def percentile(sorted_values: list[float], p: float) -> Optional[float]:
    """Nearest-rank percentile over an ascending list; None when empty."""
    if not sorted_values:
        return None
    rank = max(1, math.ceil(p / 100.0 * len(sorted_values)))
    return sorted_values[rank - 1]
