"""NodePool listing/ordering helpers (reference pkg/utils/nodepool)."""

from __future__ import annotations

from typing import Optional, Sequence

from karpenter_tpu.apis.nodepool import CONDITION_READY, NodePool
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.resources import ResourceList


def list_managed(store: Store, ready_only: bool = True) -> list[NodePool]:
    """Non-deleting (and optionally Ready) nodepools (provisioner.go:206-217)."""
    out = []
    for np in store.list("NodePool"):
        if np.metadata.deletion_timestamp is not None:
            continue
        if ready_only and not np.condition_is_true(CONDITION_READY):
            continue
        out.append(np)
    return out


def order_by_weight(node_pools: Sequence[NodePool]) -> list[NodePool]:
    """Descending weight, name tiebreak (nodepoolutils.OrderByWeight)."""
    return sorted(node_pools, key=lambda np: (-np.spec.weight, np.metadata.name))


def limits_exceeded_by(limits: ResourceList, usage: ResourceList) -> Optional[str]:
    """Error if usage exceeds any limit (v1.Limits.ExceededBy)."""
    for k, limit in limits.items():
        if usage.get(k, 0.0) > limit + 1e-9:
            return f"limit exceeded for resource {k}: used {usage.get(k, 0.0)}, limit {limit}"
    return None
