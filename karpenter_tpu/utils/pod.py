"""Pod classification predicates.

Mirrors the reference's pkg/utils/pod/scheduling.go:33-216 — which pods are
provisionable (need new capacity), reschedulable (count when simulating),
evictable/drainable (termination flow).
"""

from __future__ import annotations

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Pod
from karpenter_tpu.scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT, Taints
from karpenter_tpu.utils.clock import Clock

# Buffer past terminationGracePeriod before a terminating pod is considered
# stuck (scheduling.go:150-156).
STUCK_TERMINATING_BUFFER = 60.0

POD_SCHEDULED = "PodScheduled"
REASON_UNSCHEDULABLE = "Unschedulable"


def is_terminal(pod: Pod) -> bool:
    return pod.status.phase in ("Failed", "Succeeded")


def is_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_active(pod: Pod) -> bool:
    return not is_terminal(pod) and not is_terminating(pod)


def is_stuck_terminating(pod: Pod, clock: Clock) -> bool:
    return (
        is_terminating(pod)
        and clock.since(pod.metadata.deletion_timestamp) > STUCK_TERMINATING_BUFFER
    )


def is_owned_by(pod: Pod, kinds: tuple[str, ...]) -> bool:
    return any(ref.kind in kinds for ref in pod.metadata.owner_references)


def is_owned_by_stateful_set(pod: Pod) -> bool:
    return is_owned_by(pod, ("StatefulSet",))


def is_owned_by_daemon_set(pod: Pod) -> bool:
    return is_owned_by(pod, ("DaemonSet",))


def is_owned_by_node(pod: Pod) -> bool:
    """Static/mirror pods — unmanageable via the API server."""
    return is_owned_by(pod, ("Node",))


def has_do_not_disrupt(pod: Pod) -> bool:
    return pod.metadata.annotations.get(wk.DO_NOT_DISRUPT_ANNOTATION_KEY) == "true"


def tolerates_disrupted_no_schedule_taint(pod: Pod) -> bool:
    return Taints([DISRUPTED_NO_SCHEDULE_TAINT]).tolerates_pod(pod) is None


def failed_to_schedule(pod: Pod) -> bool:
    """kube-scheduler marked the pod PodScheduled=Unschedulable
    (scheduling.go:121-129)."""
    return any(
        c.type == POD_SCHEDULED and c.reason == REASON_UNSCHEDULABLE
        for c in pod.status.conditions
    )


def is_scheduled(pod: Pod) -> bool:
    return pod.spec.node_name != ""


def is_preempting(pod: Pod) -> bool:
    return pod.status.nominated_node_name != ""


def is_provisionable(pod: Pod) -> bool:
    """Pod needs new capacity (scheduling.go:96-107)."""
    return (
        failed_to_schedule(pod)
        and not is_scheduled(pod)
        and not is_preempting(pod)
        and not is_owned_by_daemon_set(pod)
        and not is_owned_by_node(pod)
    )


def is_reschedulable(pod: Pod) -> bool:
    """Pod counts when simulating rescheduling to new capacity
    (scheduling.go:38-48). Terminating StatefulSet pods count: the old pod
    must go before its replacement exists, so capacity is still needed."""
    return (
        (is_active(pod) or (is_owned_by_stateful_set(pod) and is_terminating(pod)))
        and not is_owned_by_daemon_set(pod)
        and not is_owned_by_node(pod)
    )


def is_evictable(pod: Pod) -> bool:
    """Karpenter will call the eviction API for this pod (scheduling.go:50-61)."""
    return (
        is_active(pod)
        and not tolerates_disrupted_no_schedule_taint(pod)
        and not is_owned_by_node(pod)
        and not has_do_not_disrupt(pod)
    )


def is_drainable(pod: Pod, clock: Clock) -> bool:
    """Node drain must wait for this pod (scheduling.go:72-85). do-not-disrupt
    pods ARE drainable — drain stalls on them even though we don't evict."""
    return (
        not tolerates_disrupted_no_schedule_taint(pod)
        and not is_stuck_terminating(pod, clock)
        and not is_owned_by_node(pod)
    )


def is_waiting_eviction(pod: Pod, clock: Clock) -> bool:
    return not is_terminal(pod) and is_drainable(pod, clock)


def is_disruptable(pod: Pod) -> bool:
    return not (is_active(pod) and has_do_not_disrupt(pod))


def is_eligible_for_forced_eviction(pod: Pod, node_grace_expiration: float | None) -> bool:
    """Pod's own grace period would overrun the node's TGP deadline
    (scheduling.go:87-94)."""
    return (
        node_grace_expiration is not None
        and is_terminating(pod)
        and pod.metadata.deletion_timestamp > node_grace_expiration
    )


def has_required_pod_anti_affinity(pod: Pod) -> bool:
    aff = pod.spec.affinity
    return (
        aff is not None
        and aff.pod_anti_affinity is not None
        and len(aff.pod_anti_affinity.required) > 0
    )


def has_pod_anti_affinity(pod: Pod) -> bool:
    aff = pod.spec.affinity
    return aff is not None and aff.pod_anti_affinity is not None and (
        len(aff.pod_anti_affinity.required) > 0
        or len(aff.pod_anti_affinity.preferred) > 0
    )
