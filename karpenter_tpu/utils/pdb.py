"""PodDisruptionBudget eviction limits.

Mirrors the reference's pkg/utils/pdb/pdb.go:44-180: can a set of pods be
evicted, and is a pod blocked from rescheduling by a fully-blocking PDB.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from karpenter_tpu.apis.core import Pod, PodDisruptionBudget
from karpenter_tpu.utils import pod as podutil

_ZERO_DISRUPTIONS = 0
_FULLY_BLOCKING = 1


@dataclass
class _PdbItem:
    key: tuple[str, str]  # (namespace, name)
    pdb: PodDisruptionBudget
    disruptions_allowed: int
    is_fully_blocking: bool
    can_always_evict_unhealthy: bool


def _new_item(pdb: PodDisruptionBudget) -> _PdbItem:
    spec = pdb.spec
    fully_blocking = (
        spec.max_unavailable in (0, "0", "0%")
        or spec.min_available == "100%"
    )
    return _PdbItem(
        key=(pdb.metadata.namespace, pdb.metadata.name),
        pdb=pdb,
        disruptions_allowed=pdb.status.disruptions_allowed,
        is_fully_blocking=fully_blocking,
        can_always_evict_unhealthy=getattr(
            spec, "unhealthy_pod_eviction_policy", None
        ) == "AlwaysAllow",
    )


class Limits(list):
    """Evaluates whether evicting pods is possible under current PDBs."""

    @classmethod
    def from_pdbs(cls, pdbs: Sequence[PodDisruptionBudget]) -> "Limits":
        return cls(_new_item(p) for p in pdbs)

    def _is_evictable(self, pod: Pod, blocker: int) -> tuple[list, bool]:
        # Non-evictable pods never hit the eviction API, so PDBs don't matter.
        if not podutil.is_evictable(pod):
            return [], True
        matching = [
            item
            for item in self
            if item.key[0] == pod.metadata.namespace
            and item.pdb.spec.selector.matches(pod.metadata.labels)
        ]
        # Kubernetes rejects eviction when >1 PDB matches a pod.
        if len(matching) > 1:
            return [i.key for i in matching], False
        for item in matching:
            if item.can_always_evict_unhealthy and any(
                c.type == "Ready" and c.status == "False"
                for c in pod.status.conditions
            ):
                return [], True
            if blocker == _ZERO_DISRUPTIONS and item.disruptions_allowed == 0:
                return [item.key], False
            if blocker == _FULLY_BLOCKING and item.is_fully_blocking:
                return [item.key], False
        return [], True

    def can_evict_pods(self, pods: Sequence[Pod]) -> tuple[list, bool]:
        """True if every pod has >0 disruptions allowed (pdb.go:63-74)."""
        for pod in pods:
            keys, ok = self._is_evictable(pod, _ZERO_DISRUPTIONS)
            if not ok:
                return keys, False
        return [], True

    def is_fully_blocked(self, pod: Pod) -> tuple[list, bool]:
        keys, ok = self._is_evictable(pod, _FULLY_BLOCKING)
        return (keys, True) if not ok else ([], False)

    def is_currently_reschedulable(self, pod: Pod) -> bool:
        """Reschedulable AND not pinned by do-not-disrupt or a fully blocking
        PDB (pdb.go:131-146): don't provision capacity for pods that can't
        actually leave their node."""
        _, blocked = self.is_fully_blocked(pod)
        return (
            podutil.is_reschedulable(pod)
            and not podutil.has_do_not_disrupt(pod)
            and not blocked
        )
