"""Event recorder with dedup + per-reason rate limiting.

Mirrors the reference's pkg/events/recorder.go:30-117: identical events are
deduplicated for a TTL window, and reasons can carry a token-bucket rate
limit so controllers can't flood the event stream.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from karpenter_tpu.utils.clock import Clock

DEDUPE_TTL = 120.0  # seconds (recorder.go:40)


@dataclass
class Event:
    involved_object: Any
    type: str  # "Normal" | "Warning"
    reason: str
    message: str
    dedupe_values: tuple = ()
    timestamp: float = 0.0

    def dedupe_key(self) -> tuple:
        if self.dedupe_values:
            return (self.reason,) + tuple(self.dedupe_values)
        obj = self.involved_object
        name = getattr(obj.metadata, "name", "") if obj is not None else ""
        return (self.type, self.reason, self.message, name)


class _TokenBucket:
    def __init__(self, rate: float, burst: int, clock: Clock):
        self.rate = rate
        self.burst = burst
        self.tokens = float(burst)
        self.last = clock.now()
        self.clock = clock

    def allow(self) -> bool:
        now = self.clock.now()
        self.tokens = min(self.burst, self.tokens + (now - self.last) * self.rate)
        self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class Recorder:
    """Publishes events, dropping duplicates within the TTL window."""

    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or Clock()
        self._seen: dict[tuple, float] = {}
        self._limiters: dict[str, _TokenBucket] = {}
        self.events: list[Event] = []

    def rate_limit(self, reason: str, rate: float = 1.0, burst: int = 10) -> None:
        self._limiters[reason] = _TokenBucket(rate, burst, self.clock)

    def publish(self, *events: Event) -> None:
        for event in events:
            key = event.dedupe_key()
            now = self.clock.now()
            self._maybe_evict(now)
            last = self._seen.get(key)
            if last is not None and now - last < DEDUPE_TTL:
                continue
            limiter = self._limiters.get(event.reason)
            if limiter is not None and not limiter.allow():
                continue
            self._seen[key] = now
            event.timestamp = now
            self.events.append(event)

    def _maybe_evict(self, now: float) -> None:
        """Prune expired dedupe entries so the map is bounded by the TTL
        window (the reference uses an expiring cache, recorder.go:48-58)."""
        if len(self._seen) < 4096:
            return
        self._seen = {k: t for k, t in self._seen.items() if now - t < DEDUPE_TTL}

    def reset(self) -> None:
        self.events.clear()
        self._seen.clear()

    def calls(self, reason: str) -> int:
        return sum(1 for e in self.events if e.reason == reason)
