"""Binary entry point: `python -m karpenter_tpu`.

Mirrors the reference's kwok binary (kwok/main.go:28-47): build the
operator with the in-tree kwok provider, wire controllers, serve
metrics/health, run the reconcile loop until signalled. The in-memory
Store stands in for the API server (SURVEY.md §5: the store is the durable
substrate; all state rebuilds from it on restart).
"""

from __future__ import annotations

import signal
import sys
import time

from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.operator import logging as klog
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.operator.serving import Server, ServingConfig
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import Clock


def main(argv=None, max_passes: int | None = None, pass_interval: float = 1.0) -> int:
    options = Options.parse(argv)
    base = {"cluster": options.cluster_name} if options.cluster_name else {}
    klog.configure(options.log_level, **base)
    log = klog.logger("operator")

    clock = Clock()
    store = Store(clock=clock)
    provider = KwokCloudProvider(store, clock)
    operator = Operator(store, provider, clock=clock, options=options)

    servers = []
    try:
        serving = ServingConfig(
            metrics_text=operator.metrics_text,
            healthy=operator.healthy,
            ready=operator.ready,
            enable_profiling=options.enable_profiling,
            solverd_stats=operator.solver_stats,
            health_snapshot=operator.health_snapshot,
            trace_snapshot=operator.trace_snapshot,
            heap_stats=operator.heap_stats,
            kernel_snapshot=operator.kernel_snapshot,
            slo_snapshot=operator.slo_snapshot,
            flight_snapshot=operator.flight_snapshot,
            device_profile=operator.device_profile_snapshot,
            journal_snapshot=operator.journal_snapshot,
            explain_snapshot=operator.explain_snapshot,
        )
        if options.metrics_port > 0:
            servers.append(Server(options.metrics_port, serving).start())
        if options.health_probe_port > 0 and options.health_probe_port != options.metrics_port:
            servers.append(Server(options.health_probe_port, serving).start())
    except OSError as e:
        log.error("failed to bind serving ports", error=str(e))
        for server in servers:
            server.stop()
        return 1

    stop = {"requested": False}

    def _signal(signum, frame):
        log.info("shutdown requested", signal=signum)
        stop["requested"] = True

    def _sigquit(signum, frame):
        # the blackbox hotkey: dump the flight ring as a postmortem bundle
        # without stopping the operator (kill -QUIT <pid>), like a JVM
        # thread dump — the recorder's cooldown keeps repeats cheap.
        # lock_timeout: the handler runs ON the main thread, which may be
        # suspended inside record() holding the recorder lock — a blocking
        # acquire would deadlock the whole operator; bounded, the dump is
        # simply skipped and the loop resumes
        bundle = operator.flight.dump("sigquit", cooldown=0.0, lock_timeout=1.0)
        if bundle is not None:
            log.info(
                "flight bundle dumped",
                bundle=bundle["name"],
                path=bundle.get("path"),
                frames=bundle["frames"],
            )

    try:
        signal.signal(signal.SIGINT, _signal)
        signal.signal(signal.SIGTERM, _signal)
        if hasattr(signal, "SIGQUIT"):
            signal.signal(signal.SIGQUIT, _sigquit)
    except ValueError:
        pass  # not the main thread (tests)

    log.info(
        "starting operator",
        provider="kwok",
        metrics_port=options.metrics_port,
        health_port=options.health_probe_port,
        feature_gates=vars(options.feature_gates),
    )
    passes = 0
    while not stop["requested"]:
        started = time.monotonic()
        try:
            operator.run_once()
        except Exception:  # noqa: BLE001 — the loop must survive
            log.error("reconcile pass failed", exc_info=True)
            # preserve the evidence: the last N passes of system state at
            # the moment the loop blew up, before retrying clobbers it
            try:
                operator.flight.dump("operator-crash")
            except Exception:  # noqa: BLE001 — the dump must not re-crash the loop
                pass
        passes += 1
        if max_passes is not None and passes >= max_passes:
            break
        delay = pass_interval - (time.monotonic() - started)
        if delay > 0 and not stop["requested"]:
            time.sleep(delay)
    operator.shutdown()
    log.info("operator stopped", passes=passes)
    for server in servers:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
