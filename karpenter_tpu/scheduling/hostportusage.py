"""HostPort conflict tracking per simulated node.

Mirrors the reference's pkg/scheduling/hostportusage.go:35-120: each
<hostIP, port, protocol> on a node must be unique; 0.0.0.0/:: wildcard IPs
conflict with everything on the same port+protocol.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.apis.core import Pod

_UNSPECIFIED = ("0.0.0.0", "::")


@dataclass(frozen=True)
class HostPort:
    ip: str
    port: int
    protocol: str

    def matches(self, other: "HostPort") -> bool:
        if self.protocol != other.protocol or self.port != other.port:
            return False
        if self.ip != other.ip and self.ip not in _UNSPECIFIED and other.ip not in _UNSPECIFIED:
            return False
        return True


def get_host_ports(pod: Pod) -> list[HostPort]:
    """Extract host ports; empty hostIP defaults to 0.0.0.0
    (hostportusage.go:95-120)."""
    out = []
    for c in list(pod.spec.containers) + list(pod.spec.init_containers):
        for p in c.ports:
            if p.host_port == 0:
                continue
            out.append(
                HostPort(ip=p.host_ip or "0.0.0.0", port=p.host_port, protocol=p.protocol)
            )
    return out


class HostPortUsage:
    def __init__(self):
        self._reserved: dict[tuple[str, str], list[HostPort]] = {}

    def __bool__(self) -> bool:
        return bool(self._reserved)

    def copy(self) -> "HostPortUsage":
        """Independent copy for simulations; HostPort entries are frozen."""
        out = HostPortUsage()
        out._reserved = {k: list(v) for k, v in self._reserved.items()}
        return out

    def add(self, pod: Pod, ports: list[HostPort]) -> None:
        self._reserved[(pod.metadata.namespace, pod.metadata.name)] = ports

    def conflicts(self, pod: Pod, ports: list[HostPort]) -> Optional[str]:
        key = (pod.metadata.namespace, pod.metadata.name)
        for new in ports:
            for pod_key, entries in self._reserved.items():
                if pod_key == key:
                    continue
                for existing in entries:
                    if new.matches(existing):
                        return (
                            f"hostPort conflict: {new.ip}:{new.port}/{new.protocol} "
                            f"vs existing {existing.ip}:{existing.port}/{existing.protocol}"
                        )
        return None

    def delete_pod(self, namespace: str, name: str) -> None:
        self._reserved.pop((namespace, name), None)
