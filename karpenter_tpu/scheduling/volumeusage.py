"""CSI volume-attach-limit accounting per simulated node.

Mirrors the reference's pkg/scheduling/volumeusage.go:43-236: pods' PVC-backed
volumes are resolved to a CSI driver (via bound PV or StorageClass
provisioner) and counted against per-driver attach limits from CSINode.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.apis.core import Pod
from karpenter_tpu.runtime.store import NotFound, Store

# In-tree provisioner names translated to their CSI equivalents
# (csi-translation-lib; only the ones the reference's tests exercise).
IN_TREE_TO_CSI = {
    "kubernetes.io/aws-ebs": "ebs.csi.aws.com",
    "kubernetes.io/gce-pd": "pd.csi.storage.gke.io",
    "kubernetes.io/azure-disk": "disk.csi.azure.com",
}


class Volumes(dict):
    """driver name → set of PVC ids (volumeusage.go:43-79)."""

    def add(self, driver: str, pvc_id: str) -> None:
        self.setdefault(driver, set()).add(pvc_id)

    def union(self, other: "Volumes") -> "Volumes":
        out = Volumes({k: set(v) for k, v in self.items()})
        for k, v in other.items():
            out.setdefault(k, set()).update(v)
        return out

    def insert(self, other: "Volumes") -> None:
        for k, v in other.items():
            self.setdefault(k, set()).update(v)


def _driver_from_volume(store: Store, volume_name: str) -> str:
    try:
        pv = store.get("PersistentVolume", volume_name)
    except NotFound:
        return ""
    return pv.csi_driver or ""


def _driver_from_storage_class(store: Store, name: str) -> Optional[str]:
    try:
        sc = store.get("StorageClass", name)
    except NotFound:
        return None
    return IN_TREE_TO_CSI.get(sc.provisioner, sc.provisioner)


def get_volumes(store: Store, pod: Pod) -> Volumes:
    """Resolve a pod's PVC-backed volumes to CSI drivers
    (volumeusage.go:81-109). Missing PVCs/StorageClasses are skipped, not
    errors — they were manually deleted and shouldn't wedge cluster state."""
    out = Volumes()
    for volume in pod.spec.volumes:
        claim_name = volume.persistent_volume_claim
        if claim_name is None and volume.ephemeral_storage_class is None:
            continue
        if claim_name is not None:
            pvc = store.try_get("PersistentVolumeClaim", claim_name, pod.metadata.namespace)
            if pvc is None:
                continue
            if pvc.volume_name:
                driver = _driver_from_volume(store, pvc.volume_name)
                if driver:
                    out.add(driver, f"{pod.metadata.namespace}/{claim_name}")
                continue
            sc_name = pvc.storage_class_name or ""
        else:
            # generic ephemeral volume: PVC named <pod>-<volume> with the
            # given storage class
            sc_name = volume.ephemeral_storage_class
            claim_name = f"{pod.metadata.name}-{volume.name}"
        if not sc_name:
            continue
        driver = _driver_from_storage_class(store, sc_name)
        if driver:
            out.add(driver, f"{pod.metadata.namespace}/{claim_name}")
    return out


class VolumeUsage:
    """Per-node volume usage vs driver limits (volumeusage.go:188-236)."""

    def __init__(self):
        self._volumes = Volumes()
        self._pod_volumes: dict[tuple[str, str], Volumes] = {}
        self._limits: dict[str, int] = {}

    def add_limit(self, driver: str, value: int) -> None:
        self._limits[driver] = value

    def copy(self) -> "VolumeUsage":
        """Independent copy for simulations (pvc-id sets copied)."""
        out = VolumeUsage()
        out._volumes = Volumes({k: set(v) for k, v in self._volumes.items()})
        out._pod_volumes = {
            pk: Volumes({k: set(v) for k, v in vols.items()})
            for pk, vols in self._pod_volumes.items()
        }
        out._limits = dict(self._limits)
        return out

    def exceeds_limits(self, vols: Volumes) -> Optional[str]:
        for driver, pvc_ids in self._volumes.union(vols).items():
            limit = self._limits.get(driver)
            if limit is not None and len(pvc_ids) > limit:
                return (
                    f"would exceed volume limit for driver {driver}: "
                    f"{len(pvc_ids)} > {limit}"
                )
        return None

    def add(self, pod: Pod, vols: Volumes) -> None:
        self._pod_volumes[(pod.metadata.namespace, pod.metadata.name)] = vols
        self._volumes = self._volumes.union(vols)

    def delete_pod(self, namespace: str, name: str) -> None:
        self._pod_volumes.pop((namespace, name), None)
        self._volumes = Volumes()
        for vols in self._pod_volumes.values():
            self._volumes.insert(vols)
