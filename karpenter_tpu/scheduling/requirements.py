"""Node-selector requirement set algebra.

Semantics mirror the reference's pkg/scheduling/requirement.go:33-350 and
requirements.go:36-298: a `Requirement` is a (possibly complemented) value
set per label key with optional integer bounds; a `Requirements` is a
key→Requirement map where adding intersects. `NotIn`/`Exists` are open-world
complement sets (infinite), which is why intersections of two complements are
always non-empty.
"""

from __future__ import annotations

from enum import Enum
from typing import Iterable, Iterator, Mapping, Optional

from karpenter_tpu.apis import labels as well_known

# Sentinel cardinality for complement (infinite) sets, mirroring the
# reference's math.MaxInt64-based Len (requirement.go:277-282).
INFINITE = 1 << 62


class Operator(str, Enum):
    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"


def _as_int(value: str) -> Optional[int]:
    try:
        return int(value)
    except ValueError:
        return None


def _within(value: str, greater_than: Optional[int], less_than: Optional[int]) -> bool:
    """Bounds check; non-integer values are invalid when bounds are set
    (reference requirement.go:308-324)."""
    if greater_than is None and less_than is None:
        return True
    iv = _as_int(value)
    if iv is None:
        return False
    if greater_than is not None and greater_than >= iv:
        return False
    if less_than is not None and less_than <= iv:
        return False
    return True


def _max_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _min_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


class Requirement:
    """A single-key requirement: value set or its complement, with bounds.

    Construction normalizes aliased label keys (requirement.go:44-84).
    """

    __slots__ = ("key", "values", "complement", "greater_than", "less_than", "min_values")

    def __init__(
        self,
        key: str,
        operator: Operator | str,
        values: Iterable[str] = (),
        min_values: Optional[int] = None,
    ):
        operator = Operator(operator)
        key = well_known.NORMALIZED_LABELS.get(key, key)
        self.key = key
        self.min_values = min_values
        self.greater_than: Optional[int] = None
        self.less_than: Optional[int] = None
        values = list(values)
        if operator == Operator.IN:
            self.values = frozenset(values)
            self.complement = False
        elif operator == Operator.DOES_NOT_EXIST:
            self.values = frozenset()
            self.complement = False
        elif operator == Operator.NOT_IN:
            self.values = frozenset(values)
            self.complement = True
        elif operator == Operator.EXISTS:
            self.values = frozenset()
            self.complement = True
        elif operator == Operator.GT:
            self.values = frozenset()
            self.complement = True
            self.greater_than = int(values[0])
        elif operator == Operator.LT:
            self.values = frozenset()
            self.complement = True
            self.less_than = int(values[0])
        else:  # pragma: no cover
            raise ValueError(f"unknown operator {operator}")

    @classmethod
    def _raw(
        cls,
        key: str,
        values: frozenset[str],
        complement: bool,
        greater_than: Optional[int] = None,
        less_than: Optional[int] = None,
        min_values: Optional[int] = None,
    ) -> "Requirement":
        r = cls.__new__(cls)
        r.key = key
        r.values = values
        r.complement = complement
        r.greater_than = greater_than
        r.less_than = less_than
        r.min_values = min_values
        return r

    # -- algebra -----------------------------------------------------------

    def intersection(self, other: "Requirement") -> "Requirement":
        """Set intersection, mirroring requirement.go:155-188."""
        complement = self.complement and other.complement
        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        min_values = _max_opt(self.min_values, other.min_values)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return Requirement(self.key, Operator.DOES_NOT_EXIST, min_values=min_values)

        if self.complement and other.complement:
            values = self.values | other.values
        elif self.complement and not other.complement:
            values = other.values - self.values
        elif not self.complement and other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = frozenset(v for v in values if _within(v, greater_than, less_than))
        if not complement:
            greater_than, less_than = None, None
        return Requirement._raw(self.key, values, complement, greater_than, less_than, min_values)

    def has_intersection(self, other: "Requirement") -> bool:
        """Allocation-free intersection test (requirement.go:194-228)."""
        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return False
        if self.complement and other.complement:
            return True
        if self.complement:
            return any(
                v not in self.values and _within(v, greater_than, less_than)
                for v in other.values
            )
        if other.complement:
            return any(
                v not in other.values and _within(v, greater_than, less_than)
                for v in self.values
            )
        return any(
            v in other.values and _within(v, greater_than, less_than) for v in self.values
        )

    def has(self, value: str) -> bool:
        """True if the requirement allows the value (requirement.go:249-254)."""
        if self.complement:
            return value not in self.values and _within(
                value, self.greater_than, self.less_than
            )
        return value in self.values and _within(value, self.greater_than, self.less_than)

    def any(self) -> str:
        """A representative allowed value (requirement.go:230-246).

        Deterministic (unlike the reference's rand) — smallest allowed value —
        so decision-identity tests are reproducible.
        """
        op = self.operator
        if op == Operator.IN:
            return min(self.values)
        if op in (Operator.NOT_IN, Operator.EXISTS):
            lo = 0 if self.greater_than is None else self.greater_than + 1
            hi = INFINITE if self.less_than is None else self.less_than
            v = lo
            while v < hi and str(v) in self.values:
                v += 1
            if v >= hi:
                return ""  # every value in (greater_than, less_than) is excluded
            return str(v)
        return ""

    @property
    def operator(self) -> Operator:
        if self.complement:
            return Operator.NOT_IN if self.values else Operator.EXISTS
        return Operator.IN if self.values else Operator.DOES_NOT_EXIST

    def __len__(self) -> int:
        if self.complement:
            return INFINITE - len(self.values)
        return len(self.values)

    def values_list(self) -> list[str]:
        return sorted(self.values)

    def insert(self, *items: str) -> None:
        self.values = frozenset(self.values | set(items))

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Requirement):
            return NotImplemented
        return (
            self.key == other.key
            and self.values == other.values
            and self.complement == other.complement
            and self.greater_than == other.greater_than
            and self.less_than == other.less_than
            and self.min_values == other.min_values
        )

    def __hash__(self) -> int:
        return hash(
            (self.key, self.values, self.complement, self.greater_than, self.less_than)
        )

    def __repr__(self) -> str:
        op = self.operator
        if op in (Operator.EXISTS, Operator.DOES_NOT_EXIST):
            s = f"{self.key} {op.value}"
        else:
            vals = self.values_list()
            if len(vals) > 5:
                vals = vals[:5] + [f"and {len(self.values) - 5} others"]
            s = f"{self.key} {op.value} {vals}"
        if self.greater_than is not None:
            s += f" >{self.greater_than}"
        if self.less_than is not None:
            s += f" <{self.less_than}"
        if self.min_values is not None:
            s += f" minValues {self.min_values}"
        return s


_LABEL_REQ_CACHE: dict = {}
_LABEL_REQ_CAP = 100_000


class Requirements:
    """A key→Requirement map where `add` intersects same-key requirements.

    Mirrors reference requirements.go:36-298.
    """

    __slots__ = ("_map",)

    def __init__(self, *requirements: Requirement):
        self._map: dict[str, Requirement] = {}
        self.add(*requirements)

    @classmethod
    def from_labels(cls, labels: Mapping[str, str]) -> "Requirements":
        # Single-value label requirements are interned process-wide: node
        # re-ingestion and consolidation simulations rebuild the same
        # (key, value) rows thousands of times per pass. Shared objects are
        # safe — nothing mutates label-derived requirements (mutation sites
        # are template minValues write-downs and topology DOES_NOT_EXIST
        # options, both operating on their own objects).
        reqs = []
        for k, v in labels.items():
            ck = (k, v)
            r = _LABEL_REQ_CACHE.get(ck)
            if r is None:
                if len(_LABEL_REQ_CACHE) >= _LABEL_REQ_CAP:
                    _LABEL_REQ_CACHE.clear()
                r = Requirement(k, Operator.IN, [v])
                _LABEL_REQ_CACHE[ck] = r
            reqs.append(r)
        return cls(*reqs)

    def copy(self) -> "Requirements":
        out = Requirements()
        out._map = dict(self._map)
        return out

    def add(self, *requirements: Requirement) -> None:
        for requirement in requirements:
            existing = self._map.get(requirement.key)
            if existing is not None:
                requirement = requirement.intersection(existing)
            self._map[requirement.key] = requirement

    def keys(self) -> set[str]:
        return set(self._map.keys())

    def values(self) -> list[Requirement]:
        return list(self._map.values())

    def __iter__(self) -> Iterator[Requirement]:
        return iter(self._map.values())

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: str) -> bool:
        return key in self._map

    def has(self, key: str) -> bool:
        return key in self._map

    def get(self, key: str) -> Requirement:
        """Missing keys behave as Exists — allow anything (requirements.go:154-160)."""
        req = self._map.get(key)
        if req is None:
            return Requirement(key, Operator.EXISTS)
        return req

    # -- compatibility -----------------------------------------------------

    def compatible(
        self, incoming: "Requirements", allow_undefined: frozenset[str] = frozenset()
    ) -> Optional[str]:
        """None if `incoming` can loosely be met, else an error string.

        Custom labels must intersect but are denied when undefined on self;
        labels in `allow_undefined` (well-known) are allowed when undefined.
        Mirrors requirements.go:175-191.
        """
        for key in incoming._map:
            if key in allow_undefined:
                continue
            op = incoming.get(key).operator
            if key in self._map or op in (Operator.NOT_IN, Operator.DOES_NOT_EXIST):
                continue
            return f"label {key!r} does not have known values"
        return self.intersects(incoming)

    def is_compatible(
        self, incoming: "Requirements", allow_undefined: frozenset[str] = frozenset()
    ) -> bool:
        """Boolean twin of compatible(): same gates, no error formatting —
        this runs in per-(pod, offering) loops."""
        for key in incoming._map:
            if key in allow_undefined:
                continue
            op = incoming.get(key).operator
            if key in self._map or op in (Operator.NOT_IN, Operator.DOES_NOT_EXIST):
                continue
            return False
        return self.intersects_ok(incoming)

    def _conflicting_pairs(self, incoming: "Requirements"):
        """Shared core of intersects()/intersects_ok(): yields each
        (key, incoming row, existing row) whose value sets don't intersect,
        honoring the NotIn/DoesNotExist double-negative carve-out
        (requirements.go:248-268)."""
        small, large = self._map, incoming._map
        if len(small) > len(large):
            small, large = large, small
        for key in small:
            if key not in large:
                continue
            existing = self.get(key)
            inc = incoming.get(key)
            if not existing.has_intersection(inc):
                if inc.operator in (Operator.NOT_IN, Operator.DOES_NOT_EXIST) and (
                    existing.operator in (Operator.NOT_IN, Operator.DOES_NOT_EXIST)
                ):
                    continue
                yield key, inc, existing

    def intersects(self, incoming: "Requirements") -> Optional[str]:
        """None if all shared keys have overlapping values, else an error
        string naming every conflict."""
        errs = [
            f"key {key}, {inc!r} not in {existing!r}"
            for key, inc, existing in self._conflicting_pairs(incoming)
        ]
        return "; ".join(errs) if errs else None

    def intersects_ok(self, incoming: "Requirements") -> bool:
        """Early-exit boolean twin of intersects()."""
        return next(iter(self._conflicting_pairs(incoming)), None) is None

    def labels(self) -> dict[str, str]:
        """Concretize to node labels, skipping restricted keys (requirements.go:270-280)."""
        out: dict[str, str] = {}
        for key, req in self._map.items():
            if not well_known.is_restricted_node_label(key):
                value = req.any()
                if value:
                    out[key] = value
        return out

    def has_min_values(self) -> bool:
        return any(r.min_values is not None for r in self._map.values())

    def node_selector_requirements(self) -> list[dict]:
        """Serialize back to NodeSelectorRequirement-shaped dicts."""
        out = []
        for r in self._map.values():
            op = r.operator
            if r.greater_than is not None:
                entry = {"key": r.key, "operator": "Gt", "values": [str(r.greater_than)]}
            elif r.less_than is not None:
                entry = {"key": r.key, "operator": "Lt", "values": [str(r.less_than)]}
            elif op in (Operator.IN, Operator.NOT_IN):
                entry = {"key": r.key, "operator": op.value, "values": r.values_list()}
            else:
                entry = {"key": r.key, "operator": op.value, "values": []}
            if r.min_values is not None:
                entry["minValues"] = r.min_values
            out.append(entry)
        return sorted(out, key=lambda e: e["key"])

    def __repr__(self) -> str:
        reqs = [
            repr(r)
            for r in self._map.values()
            if r.key not in well_known.RESTRICTED_LABELS
        ]
        return ", ".join(sorted(reqs))


ALLOW_UNDEFINED_WELL_KNOWN_LABELS = well_known.WELL_KNOWN_LABELS


def pod_requirements(pod) -> Requirements:
    """Pod requirements with the heaviest preference treated as required
    (reference requirements.go:74-76, 90-110)."""
    return _pod_requirements(pod, include_preferred=True)


def strict_pod_requirements(pod) -> Requirements:
    """Only true requirements, no preferences (requirements.go:79-81)."""
    return _pod_requirements(pod, include_preferred=False)


def _pod_requirements(pod, include_preferred: bool) -> Requirements:
    reqs = Requirements.from_labels(pod.spec.node_selector)
    affinity = pod.spec.affinity
    if affinity is None or affinity.node_affinity is None:
        return reqs
    node_affinity = affinity.node_affinity
    if include_preferred and node_affinity.preferred:
        heaviest = max(node_affinity.preferred, key=lambda p: p.weight)
        reqs.add(*requirements_from_dicts(heaviest.preference.match_expressions).values())
    # Only the first OR term is honored; the relaxation ladder removes terms
    # when unsatisfiable (requirements.go:104-108).
    if node_affinity.required:
        reqs.add(
            *requirements_from_dicts(node_affinity.required[0].match_expressions).values()
        )
    return reqs


def has_preferred_node_affinity(pod) -> bool:
    a = pod.spec.affinity
    return bool(a and a.node_affinity and a.node_affinity.preferred)


def requirements_from_dicts(raw: Iterable[Mapping]) -> Requirements:
    """Build Requirements from NodeSelectorRequirement-shaped dicts."""
    out = Requirements()
    for item in raw:
        out.add(
            Requirement(
                item["key"],
                item["operator"],
                item.get("values", ()),
                min_values=item.get("minValues"),
            )
        )
    return out
