"""Taint/toleration checks (reference pkg/scheduling/taints.go:33-81)."""

from __future__ import annotations

from typing import Iterable, Optional

from karpenter_tpu.apis import labels as well_known
from karpenter_tpu.apis.core import NO_EXECUTE, NO_SCHEDULE, Pod, Taint, Toleration

UNREGISTERED_NO_EXECUTE_TAINT = Taint(
    key=well_known.UNREGISTERED_TAINT_KEY, effect=NO_EXECUTE
)
DISRUPTED_NO_SCHEDULE_TAINT = Taint(key=well_known.DISRUPTED_TAINT_KEY, effect=NO_SCHEDULE)

# Taints expected on a node while it initializes; ignored on uninitialized
# managed nodes (reference taints.go:36-42).
KNOWN_EPHEMERAL_TAINTS: tuple[Taint, ...] = (
    Taint(key=well_known.TAINT_NODE_NOT_READY, effect=NO_SCHEDULE),
    Taint(key=well_known.TAINT_NODE_NOT_READY, effect=NO_EXECUTE),
    Taint(key=well_known.TAINT_NODE_UNREACHABLE, effect=NO_SCHEDULE),
    Taint(key=well_known.TAINT_EXTERNAL_CLOUD_PROVIDER, effect=NO_SCHEDULE, value="true"),
    UNREGISTERED_NO_EXECUTE_TAINT,
)


class Taints(list):
    """Decorated taint list (reference taints.go:45-80)."""

    def tolerates_pod(self, pod: Pod) -> Optional[str]:
        return self.tolerates(pod.spec.tolerations)

    def tolerates(self, tolerations: Iterable[Toleration]) -> Optional[str]:
        """None if every taint is tolerated, else an error string."""
        errs = []
        for taint in self:
            if not any(t.tolerates(taint) for t in tolerations):
                errs.append(
                    f"did not tolerate taint {taint.key}={taint.value}:{taint.effect}"
                )
        return "; ".join(errs) if errs else None

    def merge(self, with_taints: Iterable[Taint]) -> "Taints":
        """Union keeping self's entry on (key, effect) conflicts."""
        out = Taints(self)
        for taint in with_taints:
            if not any(taint.match(t) for t in out):
                out.append(taint)
        return out
