"""Scheduling algebra library (reference: pkg/scheduling).

Pure, dependency-free set algebra over node-selector requirements, taints,
host ports and volume usage. This is the host-side semantic twin of the
array encoding in `karpenter_tpu.ops` — property tests assert they agree.
"""

from karpenter_tpu.scheduling.requirements import (  # noqa: F401
    Operator,
    Requirement,
    Requirements,
)
from karpenter_tpu.scheduling.taints import Taint, Taints, Toleration  # noqa: F401
