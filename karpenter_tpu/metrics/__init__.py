from karpenter_tpu.metrics.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    Store,
    global_registry,
    measure,
)
