"""Prometheus-style metrics: counters, gauges, histograms, and the gauge
lifecycle Store.

Mirrors the reference's pkg/metrics/metrics.go (namespaced constructors,
Measure() duration helper) and pkg/metrics/store.go:108 (Store: replace a
family of gauges atomically per reconcile so stale series disappear).
Exposition is a text dump — there is no HTTP scrape path in-process; the
operator exposes it (operator.py).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Iterable, Optional

NAMESPACE = "karpenter"

DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _label_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


# One lock for all series mutation/exposition: the serving thread scrapes
# while the operator loop records; dict iteration during insert would
# otherwise race. Metric ops are rare enough that one lock is fine.
_LOCK = threading.Lock()


class Metric:
    def __init__(self, name: str, help: str, label_names: Iterable[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)


class Counter(Metric):
    def __init__(self, name: str, help: str, label_names: Iterable[str] = ()):
        super().__init__(name, help, label_names)
        self._values: dict[tuple, float] = {}

    def inc(self, labels: Optional[dict[str, str]] = None, value: float = 1.0) -> None:
        with _LOCK:
            self._inc(labels, value)

    def _inc(self, labels: Optional[dict[str, str]], value: float) -> None:
        key = _label_key(labels or {})
        self._values[key] = self._values.get(key, 0.0) + value

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        return self._values.get(_label_key(labels or {}), 0.0)

    def total(self) -> float:
        return sum(self._values.values())


class Gauge(Metric):
    def __init__(self, name: str, help: str, label_names: Iterable[str] = ()):
        super().__init__(name, help, label_names)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        with _LOCK:
            self._values[_label_key(labels or {})] = value

    def add(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        with _LOCK:
            self._add(value, labels)

    def _add(self, value: float, labels: Optional[dict[str, str]]) -> None:
        key = _label_key(labels or {})
        self._values[key] = self._values.get(key, 0.0) + value

    def delete(self, labels: Optional[dict[str, str]] = None) -> None:
        self._values.pop(_label_key(labels or {}), None)

    def clear(self) -> None:
        """Drop every series of this gauge family atomically — the reset
        path for families whose label sets describe evicted objects (e.g.
        per-device memory after an engine rebuild)."""
        with _LOCK:
            self._values.clear()

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        return self._values.get(_label_key(labels or {}), 0.0)

    def series(self) -> dict[tuple, float]:
        return dict(self._values)


class Histogram(Metric):
    def __init__(
        self,
        name: str,
        help: str,
        label_names: Iterable[str] = (),
        buckets: tuple = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help, label_names)
        self.buckets = buckets
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        with _LOCK:
            self._observe(value, labels)

    def _observe(self, value: float, labels: Optional[dict[str, str]]) -> None:
        key = _label_key(labels or {})
        counts = self._counts.setdefault(key, [0] * len(self.buckets))
        for i, b in enumerate(self.buckets):
            if value <= b:
                counts[i] += 1
        self._sums[key] = self._sums.get(key, 0.0) + value
        self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, labels: Optional[dict[str, str]] = None) -> int:
        return self._totals.get(_label_key(labels or {}), 0)

    def sum(self, labels: Optional[dict[str, str]] = None) -> float:
        return self._sums.get(_label_key(labels or {}), 0.0)


class Registry:
    def __init__(self):
        self._metrics: dict[str, Metric] = {}

    def counter(self, name: str, help: str = "", labels: Iterable[str] = ()) -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help, labels), Counter)

    def gauge(self, name: str, help: str = "", labels: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help, labels), Gauge)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Iterable[str] = (),
        buckets: tuple = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            name, lambda: Histogram(name, help, labels, buckets), Histogram
        )

    def _get_or_create(self, name, factory, cls):
        # registration happens at import time — including LAZY imports mid-run
        # (the first device solve pulls in ops/ffd) — so it must not race a
        # concurrent scrape iterating the metric dict
        with _LOCK:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name} already registered as {type(m).__name__}"
                )
            return m

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def expose(self) -> str:
        """Prometheus text-format dump (atomic vs concurrent recording)."""
        with _LOCK:
            return self._expose()

    def _expose(self) -> str:
        lines = []
        for m in self._metrics.values():
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {m.name} counter")
                for key, v in m._values.items():
                    lines.append(f"{m.name}{_fmt_labels(key)} {v}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {m.name} gauge")
                for key, v in m._values.items():
                    lines.append(f"{m.name}{_fmt_labels(key)} {v}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {m.name} histogram")
                for key, total in m._totals.items():
                    # bucket counts are stored cumulatively; the mandatory
                    # +Inf bucket equals _count (text exposition format)
                    counts = m._counts.get(key, [0] * len(m.buckets))
                    for bound, cumulative in zip(m.buckets, counts):
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_fmt_labels(key, le=_fmt_bound(bound))} "
                            f"{cumulative}"
                        )
                    lines.append(
                        f'{m.name}_bucket{_fmt_labels(key, le="+Inf")} {total}'
                    )
                    lines.append(f"{m.name}_sum{_fmt_labels(key)} {m._sums[key]}")
                    lines.append(f"{m.name}_count{_fmt_labels(key)} {total}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        self._metrics.clear()


def _escape_help(text: str) -> str:
    # HELP lines escape backslash and newline (not quotes)
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(value) -> str:
    # label values escape backslash, double-quote, and newline — in that
    # order, so the escaping backslashes are not themselves re-escaped
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_bound(bound: float) -> str:
    # integral bounds print without the trailing .0 (Prometheus convention:
    # le="1" and le="1.0" are DIFFERENT series to a scraper)
    return repr(float(bound)).removesuffix(".0")


def _fmt_labels(key: tuple, le: Optional[str] = None) -> str:
    pairs = list(key)
    if le is not None:
        pairs.append(("le", le))
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + inner + "}"


global_registry = Registry()


@contextmanager
def measure(histogram: Histogram, labels: Optional[dict[str, str]] = None):
    """Duration helper (pkg/metrics Measure())."""
    start = time.perf_counter()
    try:
        yield
    finally:
        histogram.observe(time.perf_counter() - start, labels)


class Store:
    """Gauge-family lifecycle manager (pkg/metrics/store.go:108): each
    Update replaces the full series set produced for an owner key, so series
    for deleted objects are removed on the next reconcile."""

    def __init__(self):
        self._owned: dict[str, list[tuple[Gauge, tuple]]] = {}

    def update(self, key: str, series: list[tuple[Gauge, dict[str, str], float]]) -> None:
        self.delete(key)
        owned = []
        for gauge, labels, value in series:
            gauge.set(value, labels)
            owned.append((gauge, _label_key(labels)))
        self._owned[key] = owned

    def delete(self, key: str) -> None:
        with _LOCK:
            for gauge, label_key in self._owned.pop(key, []):
                gauge._values.pop(label_key, None)

    def reset(self) -> None:
        for key in list(self._owned):
            self.delete(key)
