"""Cloud-provider plugin boundary (reference pkg/cloudprovider)."""

from karpenter_tpu.cloudprovider.types import (  # noqa: F401
    CloudProvider,
    CreateError,
    InstanceType,
    InstanceTypeOverhead,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    NodeClassNotReadyError,
    Offering,
    Offerings,
    RepairPolicy,
)
