"""Overlay-applying CloudProvider decorator.

Wrapped around any provider at the operator boundary when the NodeOverlay
feature gate is on, so EVERY instance-type consumer — provisioning,
consolidation simulation, drift detection, nodepool counters — sees the
same overlay-adjusted catalog. Applying per-consumer instead would let
consolidation price nodes differently than the provisioning pass that
launched them (churn loops). Launch-side application is the provider's own
concern (kwok honors overlays in create when told to).
"""

from __future__ import annotations

from karpenter_tpu.apis.nodeoverlay import OverlayApplier


class OverlayedCloudProvider:
    """Delegates everything to the wrapped provider; get_instance_types
    returns overlay-adjusted copies (memoized in OverlayApplier so object
    identity is stable across passes for downstream id-keyed caches)."""

    def __init__(self, inner, store):
        self._inner = inner
        self._applier = OverlayApplier(store)

    def get_instance_types(self, node_pool):
        return self._applier.apply(
            node_pool, self._inner.get_instance_types(node_pool)
        )

    def __getattr__(self, name):
        # see MetricsCloudProvider.__getattr__: never delegate the delegate
        # attribute itself (unpickling calls __getattr__ before __dict__ is
        # restored and would recurse)
        if name == "_inner":
            raise AttributeError(name)
        return getattr(self._inner, name)
