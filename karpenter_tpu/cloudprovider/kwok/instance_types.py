"""Generated kwok instance catalog.

Mirrors the reference's generated catalog shape (kwok/tools/
gen_instance_types.go:70-110): 12 CPU sizes × 3 memory ratios × 2 OS ×
2 arch = 144 types, each with 8 offerings (4 zones × {spot, on-demand});
price = 0.025·cpu + 0.001·GiB, spot = 0.7×.
"""

from __future__ import annotations

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider.types import (
    InstanceType,
    InstanceTypeOverhead,
    Offering,
    Offerings,
)
from karpenter_tpu.scheduling.requirements import Operator, Requirement, Requirements

CPU_SIZES = [1, 2, 4, 8, 16, 32, 48, 64, 96, 128, 192, 256]
MEM_RATIOS = {"c": 2, "s": 4, "m": 8}  # GiB per vCPU
OSES = ["linux", "windows"]
ARCHS = [wk.ARCHITECTURE_AMD64, wk.ARCHITECTURE_ARM64]
ZONES = ["kwok-zone-1", "kwok-zone-2", "kwok-zone-3", "kwok-zone-4"]
CAPACITY_TYPES = [wk.CAPACITY_TYPE_SPOT, wk.CAPACITY_TYPE_ON_DEMAND]

GIB = float(2**30)

INSTANCE_TYPE_GROUP_LABEL = "karpenter.kwok.sh/instance-group"
INSTANCE_SIZE_LABEL = "karpenter.kwok.sh/instance-size"
INSTANCE_FAMILY_LABEL = "karpenter.kwok.sh/instance-family"


def price_of(cpu: float, mem_gib: float, capacity_type: str) -> float:
    price = 0.025 * cpu + 0.001 * mem_gib
    if capacity_type == wk.CAPACITY_TYPE_SPOT:
        price *= 0.7
    return round(price, 6)


def construct_instance_types() -> list[InstanceType]:
    """Memoized: every caller shares the same InstanceType objects, so the
    provisioner's id-keyed CatalogEngine cache hits across provider
    instances (one device encode + compile per process). The returned list
    is a fresh copy; the elements are shared and must not be mutated."""
    return list(_construct_instance_types_cached())


def _construct_instance_types_cached() -> tuple[InstanceType, ...]:
    global _CATALOG
    if _CATALOG is None:
        _CATALOG = tuple(_build_instance_types())
    return _CATALOG


_CATALOG = None


def _build_instance_types() -> list[InstanceType]:
    out: list[InstanceType] = []
    for cpu in CPU_SIZES:
        for family, ratio in MEM_RATIOS.items():
            for os_name in OSES:
                for arch in ARCHS:
                    mem_gib = cpu * ratio
                    name = f"{family}-{cpu}x-{arch}-{os_name}"
                    reqs = Requirements(
                        Requirement(wk.LABEL_INSTANCE_TYPE, Operator.IN, [name]),
                        Requirement(wk.LABEL_ARCH, Operator.IN, [arch]),
                        Requirement(wk.LABEL_OS, Operator.IN, [os_name]),
                        Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ZONES),
                        Requirement(
                            wk.CAPACITY_TYPE_LABEL_KEY, Operator.IN, CAPACITY_TYPES
                        ),
                        Requirement(INSTANCE_SIZE_LABEL, Operator.IN, [f"{cpu}x"]),
                        Requirement(INSTANCE_FAMILY_LABEL, Operator.IN, [family]),
                    )
                    offerings = Offerings(
                        Offering(
                            requirements=Requirements(
                                Requirement(
                                    wk.CAPACITY_TYPE_LABEL_KEY, Operator.IN, [ct]
                                ),
                                Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, [zone]),
                            ),
                            price=price_of(cpu, mem_gib, ct),
                            available=True,
                        )
                        for zone in ZONES
                        for ct in CAPACITY_TYPES
                    )
                    capacity = {
                        wk.RESOURCE_CPU: float(cpu),
                        wk.RESOURCE_MEMORY: mem_gib * GIB,
                        wk.RESOURCE_PODS: 110.0,
                        wk.RESOURCE_EPHEMERAL_STORAGE: 20.0 * GIB,
                    }
                    overhead = InstanceTypeOverhead(
                        kube_reserved={
                            wk.RESOURCE_CPU: 0.100,
                            wk.RESOURCE_MEMORY: 0.2 * GIB,
                        }
                    )
                    out.append(
                        InstanceType(
                            name=name,
                            requirements=reqs,
                            offerings=offerings,
                            capacity=capacity,
                            overhead=overhead,
                        )
                    )
    return out
