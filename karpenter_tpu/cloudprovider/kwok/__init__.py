"""kwok-equivalent provider: fabricates Nodes directly (no kubelet), the
in-tree correctness and benchmark harness (reference kwok/)."""
