"""kwok CloudProvider: the in-tree correctness/benchmark harness.

Mirrors the reference's kwok/cloudprovider/cloudprovider.go:46-266 — Create
fabricates a Node object directly (no kubelet) after NodeRegistrationDelay;
a tick() stand-in for the kwok controller heartbeats fabricated nodes Ready.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Condition, Node, ObjectMeta, Taint
from karpenter_tpu.apis.nodeclaim import NodeClaim
from karpenter_tpu.apis.nodepool import NodePool
from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    order_by_price,
)
from karpenter_tpu.runtime.journal import IDEMPOTENCY_ANNOTATION
from karpenter_tpu.runtime.store import AlreadyExists, Store
from karpenter_tpu.scheduling.requirements import requirements_from_dicts
from karpenter_tpu.scheduling.taints import UNREGISTERED_NO_EXECUTE_TAINT
from karpenter_tpu.utils.clock import Clock

# Node appears this long after Create (kwok NodeRegistrationDelay)
NODE_REGISTRATION_DELAY = 2.0
# nodes are sharded into partitions for scale (cloudprovider.go:263-266)
PARTITION_LABEL = "kwok-partition"
NUM_PARTITIONS = 10


@dataclass
class _Instance:
    claim: NodeClaim
    instance_type: InstanceType
    node_due_at: float
    node_created: bool = False
    idempotency_key: str = ""


class KwokCloudProvider(CloudProvider):
    def __init__(self, store: Store, clock: Clock,
                 instance_types: Optional[list[InstanceType]] = None,
                 registration_delay: float = NODE_REGISTRATION_DELAY):
        self.store = store
        self.clock = clock
        self.instance_types = (
            instance_types if instance_types is not None else construct_instance_types()
        )
        self.registration_delay = registration_delay
        self._instances: dict[str, _Instance] = {}
        self._counter = 0
        # launch idempotency: key (claim annotation, runtime/journal.py) ->
        # provider id, so a retried or crash-replayed create returns the
        # instance it already acknowledged instead of launching twice
        self._keys: dict[str, str] = {}
        # key -> actual materializations, kept across deletes; any key with
        # more than one launch is a double-launch (the sim's crash sweep
        # asserts this stays zero)
        self._key_launches: dict[str, int] = {}
        self.idempotent_hits = 0
        # NodeOverlay application at launch (the provider-side half: the
        # operator wraps get_instance_types consumers with the same overlays,
        # so launch picks by the SAME adjusted prices the scheduler saw).
        # Fail-safe off; the operator enables it from the feature gate.
        self.honor_overlays = False
        from karpenter_tpu.apis.nodeoverlay import OverlayApplier

        self._overlay_applier = OverlayApplier(store)

    # -- CloudProvider boundary ---------------------------------------------

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        # key-idempotent create: the same idempotency key returns the
        # existing acknowledged instance — an ambiguous failure (ack lost
        # to a crash or a raised error) retried with the same key cannot
        # materialize a second node for one NodeClaim
        key = node_claim.metadata.annotations.get(IDEMPOTENCY_ANNOTATION, "")
        if key:
            existing = self._keys.get(key)
            if existing is not None and existing in self._instances:
                self.idempotent_hits += 1
                return copy.deepcopy(self._instances[existing].claim)
        reqs = requirements_from_dicts(node_claim.spec.requirements)
        from karpenter_tpu.utils import resources as res

        catalog = self.instance_types
        if self.honor_overlays:
            pool = self.store.try_get(
                "NodePool", node_claim.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")
            )
            catalog = self._overlay_applier.apply(pool, catalog)
        requests = node_claim.spec.resources.requests
        compatible = [
            it
            for it in catalog
            if it.requirements.intersects(reqs) is None
            and it.offerings.available().has_compatible(reqs)
            and res.fits(requests, it.allocatable())
        ]
        if not compatible:
            raise InsufficientCapacityError(
                "no compatible instance types for nodeclaim requirements"
            )
        it = order_by_price(compatible, reqs)[0]
        offering = next(
            o
            for o in sorted(it.offerings, key=lambda o: o.price)
            if o.available
            and reqs.is_compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS)
        )
        self._counter += 1
        created = copy.deepcopy(node_claim)
        created.status.provider_id = f"kwok://{node_claim.metadata.name}-{self._counter}"
        created.status.capacity = dict(it.capacity)
        created.status.allocatable = dict(it.allocatable())
        created.status.image_id = "kwok-ami"
        # Stamp every single-valued In requirement from the claim, the chosen
        # instance type, and the offering as node labels — the reference does
        # this directly, bypassing restricted-label filtering, so nodes carry
        # arch/os/zone labels (kwok/cloudprovider/cloudprovider.go:235-266).
        for source in (reqs, it.requirements, offering.requirements):
            for r in source:
                if r.operator == "In" and len(r) == 1:
                    created.metadata.labels[r.key] = r.values_list()[0]
        created.metadata.labels.update(
            {
                wk.LABEL_INSTANCE_TYPE: it.name,
                wk.LABEL_TOPOLOGY_ZONE: offering.zone,
                wk.CAPACITY_TYPE_LABEL_KEY: offering.capacity_type,
                PARTITION_LABEL: f"partition-{self._counter % NUM_PARTITIONS}",
            }
        )
        self._instances[created.status.provider_id] = _Instance(
            claim=created,
            instance_type=it,
            node_due_at=self.clock.now() + self.registration_delay,
            idempotency_key=key,
        )
        if key:
            self._keys[key] = created.status.provider_id
            self._key_launches[key] = self._key_launches.get(key, 0) + 1
        return created

    def double_launches(self) -> int:
        """Keys that materialized more than one instance — the crash-sweep
        invariant (zero, always)."""
        return sum(n - 1 for n in self._key_launches.values() if n > 1)

    def delete(self, node_claim: NodeClaim) -> None:
        pid = node_claim.status.provider_id
        if pid not in self._instances:
            raise NodeClaimNotFoundError(pid)
        inst = self._instances.pop(pid)
        if inst.idempotency_key:
            self._keys.pop(inst.idempotency_key, None)

    def get(self, provider_id: str) -> NodeClaim:
        inst = self._instances.get(provider_id)
        if inst is None:
            raise NodeClaimNotFoundError(provider_id)
        return copy.deepcopy(inst.claim)

    def list(self) -> list[NodeClaim]:
        return [copy.deepcopy(i.claim) for i in self._instances.values()]

    def get_instance_types(self, node_pool: NodePool) -> list[InstanceType]:
        return list(self.instance_types)

    def is_drifted(self, node_claim: NodeClaim) -> str:
        return ""

    def name(self) -> str:
        return "kwok"

    def reclaim(self, provider_id: str) -> bool:
        """Out-of-band capacity reclaim (a spot interruption the control
        plane never consented to): the instance vanishes without a Delete
        call, the way a real cloud takes spot capacity back. Subsequent
        get() raises NodeClaimNotFoundError and the GC controller reaps the
        claim. Returns whether the instance existed."""
        inst = self._instances.pop(provider_id, None)
        if inst is not None and inst.idempotency_key:
            self._keys.pop(inst.idempotency_key, None)
        return inst is not None

    # -- the fake kubelet (kwok controller) ---------------------------------

    def tick(self) -> int:
        """Fabricate due Nodes and heartbeat existing ones Ready
        (cloudprovider.go:58-86, 185-233). Returns nodes fabricated."""
        fabricated = 0
        now = self.clock.now()
        for inst in self._instances.values():
            if inst.node_created or now < inst.node_due_at:
                continue
            claim = inst.claim
            node = Node(
                metadata=ObjectMeta(
                    name=claim.metadata.name,
                    labels=dict(claim.metadata.labels),
                    annotations=dict(claim.metadata.annotations),
                ),
            )
            node.metadata.labels[wk.LABEL_HOSTNAME] = node.metadata.name
            node.spec.provider_id = claim.status.provider_id
            node.spec.taints = list(claim.spec.taints) + list(
                claim.spec.startup_taints
            ) + [UNREGISTERED_NO_EXECUTE_TAINT]
            node.status.capacity = dict(claim.status.capacity)
            node.status.allocatable = dict(claim.status.allocatable)
            node.status.conditions.append(
                Condition(type="Ready", status="True", reason="KubeletReady")
            )
            try:
                self.store.create(node)
            except AlreadyExists:
                pass
            inst.node_created = True
            fabricated += 1
        return fabricated
