"""CloudProvider plugin boundary: interface, InstanceType/Offering model,
typed errors. Mirrors reference pkg/cloudprovider/types.go:64-443.
"""

from __future__ import annotations

import math
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from functools import cached_property
from typing import TYPE_CHECKING, Optional, Sequence

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.scheduling.requirements import (
    Operator,
    Requirement,
    Requirements,
)
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.resources import ResourceList

if TYPE_CHECKING:
    from karpenter_tpu.apis.nodeclaim import NodeClaim
    from karpenter_tpu.apis.nodepool import NodePool

# Label injected into a reserved offering's requirements to uniquely identify
# a reservation (types.go:44-49); registered well-known in apis/labels so
# claims are compatible with reserved offerings without defining it.
RESERVATION_ID_LABEL = wk.RESERVATION_ID_LABEL_KEY

SPOT_REQUIREMENT = Requirements(
    Requirement(wk.CAPACITY_TYPE_LABEL_KEY, Operator.IN, [wk.CAPACITY_TYPE_SPOT])
)
ON_DEMAND_REQUIREMENT = Requirements(
    Requirement(wk.CAPACITY_TYPE_LABEL_KEY, Operator.IN, [wk.CAPACITY_TYPE_ON_DEMAND])
)
RESERVED_REQUIREMENT = Requirements(
    Requirement(wk.CAPACITY_TYPE_LABEL_KEY, Operator.IN, [wk.CAPACITY_TYPE_RESERVED])
)


@dataclass
class RepairPolicy:
    condition_type: str
    condition_status: str
    toleration_duration: float  # seconds


@dataclass
class Offering:
    """Where an InstanceType is available (zone × capacity-type × price).

    Requirements must contain the capacity-type and zone keys
    (types.go:255-276).
    """

    requirements: Requirements
    price: float
    available: bool = True
    reservation_capacity: int = 0

    # cached: requirements are immutable and these run in per-pod loops
    # (dataclass repr/eq use declared fields only, so the cache is inert)
    @cached_property
    def capacity_type(self) -> str:
        return self.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY).any()

    @cached_property
    def zone(self) -> str:
        return self.requirements.get(wk.LABEL_TOPOLOGY_ZONE).any()

    @cached_property
    def reservation_id(self) -> str:
        return self.requirements.get(RESERVATION_ID_LABEL).any()


class Offerings(list):
    """Offering list helpers (types.go:278-332)."""

    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def compatible(self, reqs: Requirements) -> "Offerings":
        return Offerings(
            o
            for o in self
            if reqs.is_compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS)
        )

    def has_compatible(self, reqs: Requirements) -> bool:
        return any(
            reqs.is_compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS)
            for o in self
        )

    def cheapest(self) -> Optional[Offering]:
        return min(self, key=lambda o: o.price, default=None)

    def most_expensive(self) -> Optional[Offering]:
        return max(self, key=lambda o: o.price, default=None)

    def worst_launch_price(self, reqs: Requirements) -> float:
        """Worst-case launch price by capacity-type precedence
        reserved → spot → on-demand (types.go:318-332)."""
        for ct_reqs in (RESERVED_REQUIREMENT, SPOT_REQUIREMENT, ON_DEMAND_REQUIREMENT):
            compat = self.compatible(reqs).compatible(ct_reqs)
            if compat:
                return compat.most_expensive().price
        return math.inf


@dataclass
class InstanceTypeOverhead:
    kube_reserved: ResourceList = field(default_factory=dict)
    system_reserved: ResourceList = field(default_factory=dict)
    eviction_threshold: ResourceList = field(default_factory=dict)

    def total(self) -> ResourceList:
        return res.merge(self.kube_reserved, self.system_reserved, self.eviction_threshold)


class InstanceType:
    """A potential node shape (types.go:96-125)."""

    def __init__(
        self,
        name: str,
        requirements: Requirements,
        offerings: Offerings | Sequence[Offering],
        capacity: ResourceList,
        overhead: Optional[InstanceTypeOverhead] = None,
    ):
        self.name = name
        self.requirements = requirements
        self.offerings = Offerings(offerings)
        self.capacity = capacity
        self.overhead = overhead or InstanceTypeOverhead()
        self._allocatable: Optional[ResourceList] = None

    def allocatable(self) -> ResourceList:
        if self._allocatable is None:
            self._allocatable = res.subtract(self.capacity, self.overhead.total())
        return self._allocatable

    @cached_property
    def has_reserved_offerings(self) -> bool:
        """Whether ANY offering is reserved-capacity — lets per-pod loops
        skip the offering scan for the (typical) all-unreserved catalog."""
        return any(
            o.capacity_type == wk.CAPACITY_TYPE_RESERVED for o in self.offerings
        )

    def __repr__(self) -> str:
        return f"InstanceType({self.name})"


def order_by_price(
    instance_types: Sequence[InstanceType], reqs: Requirements
) -> list[InstanceType]:
    """Sort by cheapest available compatible offering (types.go:127-146).
    Stable, so equal-price types keep their input order (decision identity)."""

    def price(it: InstanceType) -> float:
        best = math.inf
        for o in it.offerings:
            if (
                o.available
                and reqs.is_compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS)
                and o.price < best
            ):
                best = o.price
        return best

    return sorted(instance_types, key=price)


def compatible_instance_types(
    instance_types: Sequence[InstanceType], requirements: Requirements
) -> list[InstanceType]:
    """Filter to types with an available compatible offering (types.go:149-157)."""
    return [
        it
        for it in instance_types
        if it.offerings.available().has_compatible(requirements)
    ]


def satisfies_min_values(
    instance_types: Sequence[InstanceType], requirements: Requirements
) -> tuple[int, dict[str, int], Optional[str]]:
    """Minimum instance types needed to satisfy minValues requirements.

    Returns (min_needed, unsatisfiable_keys, error). Mirrors
    types.go:190-224 — order-dependent, callers sort by price first.
    """
    if not requirements.has_min_values():
        return 0, {}, None
    incompatible: dict[str, int] = {}
    values_for_key: dict[str, set[str]] = {}
    min_reqs = [r for r in requirements if r.min_values is not None]
    for i, it in enumerate(instance_types):
        for req in min_reqs:
            values_for_key.setdefault(req.key, set()).update(
                it.requirements.get(req.key).values
            )
        for k, vals in values_for_key.items():
            needed = requirements.get(k).min_values or 0
            if len(vals) < needed:
                incompatible[k] = len(vals)
            else:
                incompatible.pop(k, None)
        if not incompatible:
            return i + 1, {}, None
    if incompatible:
        return (
            len(instance_types),
            incompatible,
            min_values_error(incompatible),
        )
    return len(instance_types), {}, None


def min_values_error(keys) -> str:
    """The user-facing minValues failure text (types.go:218). Shared with the
    device solver's diversity gate (ops/ffd.py _min_fail) — host/device
    decision parity compares error STRINGS, so there must be one source."""
    return f"minValues requirement is not met for label(s) {sorted(keys)}"


def truncate_instance_types(
    instance_types: Sequence[InstanceType],
    requirements: Requirements,
    max_items: int,
    best_effort_min_values: bool = False,
) -> tuple[list[InstanceType], Optional[str]]:
    """Price-ordered truncation honoring minValues (types.go:228-240)."""
    truncated = order_by_price(instance_types, requirements)[:max_items]
    if requirements.has_min_values() and not best_effort_min_values:
        _, _, err = satisfies_min_values(truncated, requirements)
        if err is not None:
            return list(instance_types), f"validating minValues, {err}"
    return truncated, None


# -- typed errors (types.go:334-443) ---------------------------------------


class NodeClaimNotFoundError(Exception):
    pass


class InsufficientCapacityError(Exception):
    pass


class NodeClassNotReadyError(Exception):
    pass


class CreateError(Exception):
    def __init__(self, message: str, condition_reason: str = "", condition_message: str = ""):
        super().__init__(message)
        self.condition_reason = condition_reason
        self.condition_message = condition_message or message


class CircuitBreakerOpenError(CreateError):
    """Fast-fail: the provider circuit breaker is open — the cloud has been
    failing consecutively and calls are shed until the next probe window.
    Subclasses CreateError so launch paths degrade through the normal
    typed-error handling (condition set, claim retried) instead of crashing;
    delete paths surface it to the reconciler harness for backoff."""

    def __init__(self, message: str, retry_after: float = 0.0):
        super().__init__(message, condition_reason="CloudProviderCircuitOpen")
        self.retry_after = retry_after


def is_retryable_error(e: BaseException) -> bool:
    """Whether a cloud call failure is infrastructure-shaped (worth a retry,
    counted by the circuit breaker) rather than a domain answer. Not-found,
    insufficient capacity, and nodeclass-not-ready are the cloud RESPONDING
    — they break a consecutive-failure streak instead of extending it. A
    breaker fast-fail is itself never evidence about the cloud."""
    return not isinstance(
        e,
        (
            NodeClaimNotFoundError,
            InsufficientCapacityError,
            NodeClassNotReadyError,
            CircuitBreakerOpenError,
        ),
    )


class CloudProvider(ABC):
    """The pluggable provider boundary (types.go:64-92)."""

    @abstractmethod
    def create(self, node_claim: "NodeClaim") -> "NodeClaim":
        """Launch a NodeClaim; returns it hydrated with resolved labels.
        Raises InsufficientCapacityError / NodeClassNotReadyError /
        CreateError on failure."""

    @abstractmethod
    def delete(self, node_claim: "NodeClaim") -> None:
        """Terminate; raises NodeClaimNotFoundError once gone."""

    @abstractmethod
    def get(self, provider_id: str) -> "NodeClaim":
        """Fetch by provider id; raises NodeClaimNotFoundError."""

    @abstractmethod
    def list(self) -> list["NodeClaim"]:
        ...

    @abstractmethod
    def get_instance_types(self, node_pool: "NodePool") -> list[InstanceType]:
        """All instance types, including ones with no available offerings."""

    @abstractmethod
    def is_drifted(self, node_claim: "NodeClaim") -> str:
        """Returns a drift reason, or '' if not drifted."""

    def repair_policies(self) -> list[RepairPolicy]:
        return []

    @abstractmethod
    def name(self) -> str:
        ...
