"""Cloud-provider circuit breaker decorator.

Wraps any CloudProvider so ``create``/``delete`` flow through one
CircuitBreaker (operator/harness.py): after N consecutive retryable
failures the breaker opens and both methods fast-fail with the typed
``CircuitBreakerOpenError`` instead of hammering a broken cloud every
reconcile pass; after the cooldown one half-open probe is let through, and
its outcome closes or re-opens the breaker.

Layering (operator.py): Breaker(Metrics(provider)) — the metrics decorator
sits INSIDE so fast-fails are never miscounted as provider errors or
latency; only calls that actually reach the cloud are metered.

Read-side methods (get/list/get_instance_types/is_drifted) bypass the
breaker: they are cheap, their staleness is tolerable, and blocking them
would blind the very controllers that drain a broken cloud's state.
"""

from __future__ import annotations

from karpenter_tpu import tracing
from karpenter_tpu.cloudprovider.types import (
    CircuitBreakerOpenError,
    is_retryable_error,
)
from karpenter_tpu.metrics import global_registry
from karpenter_tpu.operator import logging as klog
from karpenter_tpu.operator.harness import CircuitBreaker
from karpenter_tpu.utils.clock import Clock

_log = klog.logger("cloudprovider.breaker")

_STATE_VALUES = {
    CircuitBreaker.CLOSED: 0.0,
    CircuitBreaker.HALF_OPEN: 1.0,
    CircuitBreaker.OPEN: 2.0,
}
_STATE = global_registry.gauge(
    "karpenter_cloudprovider_circuit_breaker_state",
    "circuit breaker state (0 closed, 1 half-open, 2 open)",
    labels=["provider"],
)
_TRANSITIONS = global_registry.counter(
    "karpenter_cloudprovider_circuit_breaker_transitions_total",
    "circuit breaker state transitions",
    labels=["provider", "to"],
)


class BreakerCloudProvider:
    """CircuitBreaker around create/delete; everything else delegates."""

    def __init__(
        self,
        inner,
        clock: Clock,
        threshold: int = 5,
        cooldown: float = 30.0,
    ):
        self._inner = inner
        try:
            provider = inner.name()
        except Exception:  # noqa: BLE001 — name() must not break wrapping
            provider = type(inner).__name__
        self.breaker = CircuitBreaker(
            clock, threshold=threshold, cooldown=cooldown, name=provider
        )
        self.breaker.subscribe(self._on_transition)
        _STATE.set(0.0, {"provider": provider})

    def _on_transition(self, old: str, new: str) -> None:
        _STATE.set(_STATE_VALUES[new], {"provider": self.breaker.name})
        _TRANSITIONS.inc({"provider": self.breaker.name, "to": new})
        _log.warning(
            "cloud provider circuit breaker transition",
            provider=self.breaker.name,
            **{"from": old, "to": new},
        )

    def _guarded(self, method: str, *args):
        # every guarded call is a span carrying breaker state — nested under
        # whatever journey hop invoked it (nodeclaim.launch, finalization),
        # so a fast-fail shows up in the pod's trace as exactly that
        with tracing.tracer().span(
            f"cloudprovider.{method}", breaker_state=self.breaker.state
        ) as span:
            if not self.breaker.allow():
                retry_after = self.breaker.retry_after()
                span.set_attr(fast_fail=True)
                raise CircuitBreakerOpenError(
                    f"cloud provider circuit breaker open for {method!r} "
                    f"(retry in {retry_after:.1f}s)",
                    retry_after=retry_after,
                )
            # allow() may have transitioned open -> half-open: record the
            # state the call actually ran under
            span.set_attr(breaker_state=self.breaker.state)
            try:
                result = getattr(self._inner, method)(*args)
            except Exception as e:
                if is_retryable_error(e):
                    self.breaker.record_failure()
                    span.set_attr(retryable=True)
                else:
                    # a typed domain answer: the cloud is alive and responding
                    self.breaker.record_success()
                    span.set_attr(retryable=False)
                raise
            self.breaker.record_success()
            return result

    def create(self, node_claim):
        return self._guarded("create", node_claim)

    def delete(self, node_claim):
        return self._guarded("delete", node_claim)

    def get(self, provider_id):
        return self._inner.get(provider_id)

    def list(self):
        return self._inner.list()

    def get_instance_types(self, node_pool):
        return self._inner.get_instance_types(node_pool)

    def is_drifted(self, node_claim):
        return self._inner.is_drifted(node_claim)

    def repair_policies(self):
        return self._inner.repair_policies()

    def name(self):
        return self._inner.name()

    def __getattr__(self, attr):
        # guard the delegate attribute itself: during unpickling __getattr__
        # runs before __dict__ is restored, and delegating a missing _inner
        # to itself recurses forever
        if attr == "_inner":
            raise AttributeError(attr)
        return getattr(self._inner, attr)
