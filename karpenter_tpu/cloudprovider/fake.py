"""Scriptable fake CloudProvider for tests.

Mirrors the reference's pkg/cloudprovider/fake/cloudprovider.go:64-240 —
records Create/Delete calls, injects errors, serves per-nodepool instance
types, and fabricates hydrated NodeClaims with resolved labels.
"""

from __future__ import annotations

import copy
from typing import Optional

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import NodeClaim
from karpenter_tpu.apis.nodepool import NodePool
from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    NodeClaimNotFoundError,
    RepairPolicy,
    order_by_price,
)
from karpenter_tpu.scheduling.requirements import requirements_from_dicts


class FakeCloudProvider(CloudProvider):
    def __init__(self, instance_types: Optional[list[InstanceType]] = None):
        self.instance_types = (
            instance_types if instance_types is not None else construct_instance_types()
        )
        self.instance_types_for_nodepool: dict[str, list[InstanceType]] = {}
        self.created: dict[str, NodeClaim] = {}  # provider id -> claim
        self.create_calls: list[NodeClaim] = []
        self.delete_calls: list[NodeClaim] = []
        self.next_create_err: Optional[Exception] = None
        self.next_get_err: Optional[Exception] = None
        self.next_delete_err: Optional[Exception] = None
        self.drifted: str = ""
        self._repair_policies: list[RepairPolicy] = []
        self._counter = 0

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        self.create_calls.append(node_claim)
        if self.next_create_err is not None:
            err, self.next_create_err = self.next_create_err, None
            raise err
        from karpenter_tpu.utils import resources as res

        reqs = requirements_from_dicts(node_claim.spec.requirements)
        requests = node_claim.spec.resources.requests
        compatible = [
            it
            for it in self.get_instance_types_by_name(
                node_claim.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")
            )
            if it.requirements.intersects(reqs) is None
            and it.offerings.available().has_compatible(reqs)
            and res.fits(requests, it.allocatable())
        ]
        if not compatible:
            from karpenter_tpu.cloudprovider.types import InsufficientCapacityError

            raise InsufficientCapacityError("no compatible instance types")
        it = order_by_price(compatible, reqs)[0]
        offering = next(
            o
            for o in it.offerings
            if o.available
            and reqs.is_compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS)
        )
        self._counter += 1
        created = copy.deepcopy(node_claim)
        created.status.provider_id = f"fake://{node_claim.metadata.name}-{self._counter}"
        created.status.capacity = dict(it.capacity)
        created.status.allocatable = dict(it.allocatable())
        # requirement-derived labels first; the chosen offering's zone and
        # capacity type must win (the node IS where it launched)
        created.metadata.labels.update(reqs.labels())
        created.metadata.labels.update(
            {
                wk.LABEL_INSTANCE_TYPE: it.name,
                wk.LABEL_TOPOLOGY_ZONE: offering.zone,
                wk.CAPACITY_TYPE_LABEL_KEY: offering.capacity_type,
            }
        )
        created.status.image_id = "fake-image"
        self.created[created.status.provider_id] = created
        return created

    def delete(self, node_claim: NodeClaim) -> None:
        self.delete_calls.append(node_claim)
        if self.next_delete_err is not None:
            err, self.next_delete_err = self.next_delete_err, None
            raise err
        if node_claim.status.provider_id not in self.created:
            raise NodeClaimNotFoundError(node_claim.status.provider_id)
        del self.created[node_claim.status.provider_id]

    def get(self, provider_id: str) -> NodeClaim:
        if self.next_get_err is not None:
            err, self.next_get_err = self.next_get_err, None
            raise err
        claim = self.created.get(provider_id)
        if claim is None:
            raise NodeClaimNotFoundError(provider_id)
        return copy.deepcopy(claim)

    def list(self) -> list[NodeClaim]:
        return [copy.deepcopy(c) for c in self.created.values()]

    def get_instance_types(self, node_pool: NodePool) -> list[InstanceType]:
        return self.get_instance_types_by_name(node_pool.metadata.name)

    def get_instance_types_by_name(self, name: str) -> list[InstanceType]:
        return self.instance_types_for_nodepool.get(name, self.instance_types)

    def is_drifted(self, node_claim: NodeClaim) -> str:
        return self.drifted

    def repair_policies(self) -> list[RepairPolicy]:
        return self._repair_policies

    def name(self) -> str:
        return "fake"

    def reset(self) -> None:
        self.__init__(self.instance_types)
