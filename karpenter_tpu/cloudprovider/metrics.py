"""CloudProvider metrics decorator.

Mirrors the reference's pkg/cloudprovider/metrics/cloudprovider.go: wraps
any provider so every interface method records a duration histogram and an
error counter (labeled by method, provider, and error type). The operator
wraps the provider by default, so provider latency/fault visibility needs
no provider cooperation.
"""

from __future__ import annotations

import time

from karpenter_tpu.cloudprovider.types import is_retryable_error
from karpenter_tpu.metrics import global_registry

_DURATION = global_registry.histogram(
    "karpenter_cloudprovider_duration_seconds",
    "duration of cloud provider method calls",
    labels=("controller", "method", "provider"),
)
_ERRORS = global_registry.counter(
    "karpenter_cloudprovider_errors_total",
    "total errors returned from cloud provider methods",
    labels=("controller", "method", "provider", "error", "retryable"),
)

class MetricsCloudProvider:
    """Duration/error instrumentation around every provider method; all
    other attributes delegate to the wrapped provider."""

    def __init__(self, inner, controller: str = ""):
        self._inner = inner
        self._controller = controller
        try:
            self._provider = inner.name()
        except Exception:  # noqa: BLE001 — name() must not break wrapping
            self._provider = type(inner).__name__

    def _call(self, method: str, *args, **kwargs):
        labels = {
            "controller": self._controller,
            "method": method,
            "provider": self._provider,
        }
        start = time.perf_counter()
        try:
            return getattr(self._inner, method)(*args, **kwargs)
        except Exception as e:
            # retryable distinguishes infrastructure failures (what the
            # circuit breaker counts) from typed domain answers like
            # not-found — an alert on retryable=true is an outage signal
            _ERRORS.inc(
                {
                    **labels,
                    "error": type(e).__name__,
                    "retryable": "true" if is_retryable_error(e) else "false",
                }
            )
            raise
        finally:
            _DURATION.observe(time.perf_counter() - start, labels)

    def create(self, node_claim):
        return self._call("create", node_claim)

    def delete(self, node_claim):
        return self._call("delete", node_claim)

    def get(self, provider_id):
        return self._call("get", provider_id)

    def list(self):
        return self._call("list")

    def get_instance_types(self, node_pool):
        return self._call("get_instance_types", node_pool)

    def is_drifted(self, node_claim):
        return self._call("is_drifted", node_claim)

    def repair_policies(self):
        return self._call("repair_policies")

    def name(self):
        return self._inner.name()

    def __getattr__(self, attr):
        # guard the delegate attribute itself: during unpickling __getattr__
        # runs before __dict__ is restored, and delegating a missing _inner
        # to itself recurses forever
        if attr == "_inner":
            raise AttributeError(attr)
        return getattr(self._inner, attr)
